"""Small multi-agent example envs for tests and tuned examples
(reference: rllib/examples/env/ — two-step game, coordination tasks)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv

try:
    from gymnasium import spaces
except ImportError:  # pragma: no cover
    spaces = None


class CoordinationGameEnv(MultiAgentEnv):
    """Cooperative context matching (QMIX's home turf): each round both
    agents observe the same one-hot context and must BOTH play the action
    equal to the context index to score — the team earns 1.0 only on
    joint success, split evenly, so credit assignment runs through the
    team reward. ``rounds`` rounds per episode; optimal team return =
    rounds; uniform-random = rounds / actions^2."""

    def __init__(self, config: Optional[dict] = None):
        config = dict(config or {})
        self.rounds = int(config.get("rounds", 10))
        self.n_contexts = int(config.get("n_contexts", 2))
        self.n_actions = int(config.get("n_actions", 3))
        self._seed = int(config.get("seed", 0))
        self.agent_ids = {"a0", "a1"}
        self._rng = np.random.default_rng(self._seed)
        if spaces is not None:
            self.observation_space = spaces.Box(
                0.0, 1.0, (self.n_contexts,), np.float32)
            self.action_space = spaces.Discrete(self.n_actions)
        self._t = 0
        self._ctx = 0

    def _obs(self):
        onehot = np.zeros(self.n_contexts, np.float32)
        onehot[self._ctx] = 1.0
        return {"a0": onehot.copy(), "a1": onehot.copy()}

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._t = 0
        self._ctx = int(self._rng.integers(self.n_contexts))
        return self._obs(), {}

    def step(self, action_dict):
        match = all(int(action_dict[aid]) == self._ctx
                    for aid in ("a0", "a1"))
        r = 0.5 if match else 0.0
        self._t += 1
        done = self._t >= self.rounds
        self._ctx = int(self._rng.integers(self.n_contexts))
        obs = self._obs()
        rewards = {"a0": r, "a1": r}
        terms = {"a0": done, "a1": done, "__all__": done}
        truncs = {"a0": False, "a1": False, "__all__": False}
        return obs, rewards, terms, truncs, {}
