"""WorkerSet: the fleet of rollout actors.

Analog of the reference's rllib/evaluation/worker_set.py:78: creates N
RolloutWorker actors, broadcasts weights, gathers sampled batches and
episode stats in parallel.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import ray_tpu
from ray_tpu.rllib.evaluation.rollout_worker import RolloutWorker
from ray_tpu.rllib.policy.sample_batch import SampleBatch


class WorkerSet:
    def __init__(self, env_creator: Callable, policy_config: Dict[str, Any],
                 num_workers: int, seed: int = 0,
                 num_cpus_per_worker: float = 1.0):
        self.is_multi_agent = bool(policy_config.get("policies"))
        if self.is_multi_agent:
            from ray_tpu.rllib.evaluation.multi_agent_worker import (
                MultiAgentRolloutWorker)
            worker_cls = MultiAgentRolloutWorker
        else:
            worker_cls = RolloutWorker
        # Workers that derive per-worker state (APEX exploration epsilons)
        # need to know the fleet size.
        policy_config = dict(policy_config, num_workers=num_workers)
        cls = ray_tpu.remote(worker_cls)
        self._workers = [
            cls.options(num_cpus=num_cpus_per_worker).remote(
                env_creator, policy_config, worker_index=i + 1, seed=seed)
            for i in range(num_workers)]

    @property
    def remote_workers(self) -> List[Any]:
        return self._workers

    def num_workers(self) -> int:
        return len(self._workers)

    def sync_weights(self, weights_ref) -> None:
        ray_tpu.get([w.set_weights.remote(weights_ref)
                     for w in self._workers])

    def sample(self, steps_per_worker: int):
        batches = ray_tpu.get([w.sample.remote(steps_per_worker)
                               for w in self._workers])
        if self.is_multi_agent:
            from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch
            return MultiAgentBatch.concat_samples(batches)
        return SampleBatch.concat_samples(batches)

    def episode_stats(self) -> Dict[str, float]:
        import numpy as np
        stats = ray_tpu.get([w.episode_stats.remote()
                             for w in self._workers])
        rewards = [s["episode_reward_mean"] for s in stats
                   if s["episodes"] > 0]
        lengths = [s["episode_len_mean"] for s in stats
                   if s["episodes"] > 0]
        return {
            "episodes_total": sum(s["episodes"] for s in stats),
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "episode_len_mean": float(np.mean(lengths)) if lengths
            else float("nan"),
        }

    def stop(self) -> None:
        for w in self._workers:
            ray_tpu.kill(w)
