"""RolloutWorker: environment-sampling actor.

Analog of the reference's rllib/evaluation/rollout_worker.py:165 (sample
:878): owns env instances + a policy copy, steps them for
rollout_fragment_length, postprocesses (GAE for actor-critic policies; raw
transitions for off-policy ones), returns a SampleBatch. Created as actors
by WorkerSet; weights sync via set_weights before every sampling round.
Observations/actions pass through connector pipelines
(rllib/connectors/connector.py), and sampled batches can be mirrored to
offline JSON output (rllib/offline/json_writer.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import numpy as np

from ray_tpu.rllib.connectors import get_connectors
from ray_tpu.rllib.policy import make_policy
from ray_tpu.rllib.policy.jax_policy import compute_gae
from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _make_env(env_creator, env_config):
    env = env_creator(env_config or {})
    from ray_tpu.rllib.env.external_env import ExternalEnv, GymAdapter
    if isinstance(env, ExternalEnv):
        # Self-driving env (reference: external_env.py ExternalEnvWrapper):
        # invert its queue protocol back into reset()/step() so the
        # standard samplers (and their batched inference) drive it.
        return GymAdapter(env)
    return env


def _pin_rollout_backend(backend) -> None:
    """Pin THIS process's jax platform for sampling (reference: rollout
    workers are CPU samplers; the learner owns the accelerator). In a
    fresh daemon/worker process jax would otherwise grab the TPU
    backend — per-step small-batch inference over a remote-chip tunnel
    measures tunnel latency (~150ms/step: the 14x daemon-rollout
    slowdown), and a pod of samplers would fight the learner for its
    chip. No-op once jax is initialized: driver-resident workers share
    the learner's process and must not flip its platform."""
    if not backend:
        return
    try:
        import jax
        from jax._src import xla_bridge
        if not getattr(xla_bridge, "_backends", None):
            jax.config.update("jax_platforms", backend)
    except Exception:  # noqa: BLE001 - sampling works on any backend
        pass


class RolloutWorker:
    def __init__(self, env_creator: Callable, policy_config: Dict[str, Any],
                 worker_index: int = 0, seed: int = 0):
        _pin_rollout_backend(policy_config.get("rollout_backend", "cpu"))
        import jax
        self.env = _make_env(env_creator, policy_config.get("env_config"))
        obs_space = self.env.observation_space
        self.policy = make_policy(policy_config, obs_space,
                                  self.env.action_space,
                                  seed=seed + worker_index)
        self.obs_connectors, self.action_connectors = get_connectors(
            policy_config, obs_space, self.env.action_space)
        if policy_config.get("per_worker_epsilon") and \
                hasattr(self.policy, "epsilon"):
            # APEX exploration ladder (Horgan et al. 2018): worker i of N
            # keeps a FIXED epsilon = 0.4^(1 + 7*i/(N-1)) — a spread of
            # exploration rates instead of one central schedule.
            n = max(int(policy_config.get("num_workers", 1)), 1)
            alpha = 7.0
            frac = (worker_index - 1) / max(n - 1, 1)
            self.policy.epsilon = 0.4 ** (1.0 + alpha * frac)
            self.policy.fixed_epsilon = True
        self.gamma = policy_config.get("gamma", 0.99)
        self.lam = policy_config.get("lambda", 0.95)
        self.worker_index = worker_index
        self._key = jax.random.PRNGKey(1000 + seed + worker_index)
        self._obs, _ = self.env.reset(seed=seed + worker_index)
        self._eps_id = worker_index * 1_000_000
        # Vectorized sampling (reference: num_envs_per_worker) batches
        # policy inference over N sibling envs — one forward pass per
        # step for ALL envs, the sampler-throughput lever. Recurrent
        # policies (per-episode hidden state rows) stay on the serial
        # path.
        self.num_envs = max(int(policy_config.get(
            "num_envs_per_worker", 1) or 1), 1)
        # hasattr, not truthiness: recurrent policies expose state_rows
        # from construction but only fill it after the first step.
        if self.num_envs > 1 and not hasattr(self.policy, "state_rows"):
            from ray_tpu.rllib.connectors import get_connectors as _gc
            self._vec_envs = [self.env]
            self._vec_obs_conn = [self.obs_connectors]
            for i in range(1, self.num_envs):
                env_i = _make_env(env_creator,
                                  policy_config.get("env_config"))
                obs_conn_i, _ = _gc(policy_config, obs_space,
                                    env_i.action_space)
                self._vec_envs.append(env_i)
                self._vec_obs_conn.append(obs_conn_i)
            self._vec_obs = [self._obs] + [
                e.reset(seed=seed + worker_index + 7919 * i)[0]
                for i, e in enumerate(self._vec_envs) if i > 0]
            self._vec_eps = [self._eps_id + i
                             for i in range(self.num_envs)]
            self._eps_id += self.num_envs
            self._vec_ep_reward = [0.0] * self.num_envs
            self._vec_ep_len = [0] * self.num_envs
        else:
            self.num_envs = 1
        self._episode_reward = 0.0
        self._episode_len = 0
        self.completed_rewards: list = []
        self.completed_lengths: list = []
        self._writer = None
        output_dir = policy_config.get("output")
        if output_dir:
            from ray_tpu.rllib.offline.json_writer import JsonWriter
            self._writer = JsonWriter(output_dir, worker_index=worker_index)

    def set_weights(self, weights) -> bool:
        self.policy.set_weights(weights)
        return True

    def apply(self, fn, *args, **kwargs):
        """Run ``fn(self, ...)`` on the worker (reference:
        RolloutWorker.apply) — the seam algorithm-owned worker-side
        logic ships through (DDPPO's decentralized learner lives in a
        function applied here)."""
        return fn(self, *args, **kwargs)

    def init_collective_group(self, world_size: int, rank: int,
                              backend: str = "tpu",
                              group_name: str = "default"):
        """Join a collective group (util/collective) from this worker —
        what create_collective_group invokes (DDPPO's gradient
        allreduce ring spans the rollout workers)."""
        from ray_tpu.util import collective
        collective.init_collective_group(world_size, rank, backend,
                                         group_name)
        return rank

    def get_weights(self):
        return self.policy.get_weights()

    def sample(self, num_steps: int) -> SampleBatch:
        if self.num_envs > 1:
            return self._sample_vectorized(num_steps)
        import jax
        rows = {k: [] for k in (
            SampleBatch.OBS, SampleBatch.NEXT_OBS, SampleBatch.ACTIONS,
            SampleBatch.REWARDS, SampleBatch.TERMINATEDS,
            SampleBatch.TRUNCATEDS, SampleBatch.ACTION_LOGP,
            SampleBatch.VF_PREDS, SampleBatch.EPS_ID)}
        keyed = getattr(self.policy, "compute_actions_keyed", None)
        for _ in range(num_steps):
            obs = np.asarray(self.obs_connectors(self._obs))
            if keyed is not None:
                action, logp, value, self._key = keyed(obs[None],
                                                       self._key)
            else:
                self._key, sub = jax.random.split(self._key)
                action, logp, value = self.policy.compute_actions(
                    obs[None], sub)
            # Recurrent policies publish their PRE-step hidden state per
            # transition (R2D2: the learner re-seeds the recurrence from
            # any stored window start).
            for k, v in getattr(self.policy, "state_rows", {}).items():
                rows.setdefault(k, []).append(v)
            act = action[0]
            act_env = int(act) if self.policy.discrete else np.asarray(act)
            if self.action_connectors.connectors:
                act_env = self.action_connectors(act_env)
            nxt, reward, terminated, truncated, _ = self.env.step(act_env)
            # NEXT_OBS passes the pipeline read-only: it must see the same
            # normalization as OBS, but stateful filters only consume each
            # frame once (at its OBS position next iteration).
            rows[SampleBatch.OBS].append(obs)
            rows[SampleBatch.NEXT_OBS].append(
                np.asarray(self.obs_connectors.apply_readonly(nxt)))
            rows[SampleBatch.ACTIONS].append(act)
            rows[SampleBatch.REWARDS].append(np.float32(reward))
            rows[SampleBatch.TERMINATEDS].append(np.float32(terminated))
            rows[SampleBatch.TRUNCATEDS].append(np.float32(truncated))
            rows[SampleBatch.ACTION_LOGP].append(logp[0])
            rows[SampleBatch.VF_PREDS].append(value[0])
            rows[SampleBatch.EPS_ID].append(self._eps_id)
            self._episode_reward += float(reward)
            self._episode_len += 1
            if terminated or truncated:
                self.completed_rewards.append(self._episode_reward)
                self.completed_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
                reset_state = getattr(self.policy, "reset_state", None)
                if callable(reset_state):
                    reset_state()  # recurrent state dies with the episode
            else:
                self._obs = nxt
        batch = self._postprocess(SampleBatch(rows))
        if self._writer is not None:
            self._writer.write(batch)
        return batch

    def _sample_vectorized(self, num_steps: int) -> SampleBatch:
        """Round-robin N envs with BATCHED policy inference; emits
        ceil(num_steps / N) steps per env. Each env keeps its own
        stateful obs-connector pipeline, episode ids, and GAE bootstrap
        (postprocessed per env so value targets never cross envs)."""
        import jax
        import numpy as np
        steps_per_env = max((num_steps + self.num_envs - 1) //
                            self.num_envs, 1)
        N = self.num_envs
        per_env_rows = [
            {k: [] for k in (
                SampleBatch.OBS, SampleBatch.NEXT_OBS,
                SampleBatch.ACTIONS, SampleBatch.REWARDS,
                SampleBatch.TERMINATEDS, SampleBatch.TRUNCATEDS,
                SampleBatch.ACTION_LOGP, SampleBatch.VF_PREDS,
                SampleBatch.EPS_ID)}
            for _ in range(N)]
        keyed = getattr(self.policy, "compute_actions_keyed", None)
        for _ in range(steps_per_env):
            obs_batch = np.stack([
                np.asarray(self._vec_obs_conn[i](self._vec_obs[i]))
                for i in range(N)])
            if keyed is not None:
                actions, logps, values, self._key = keyed(obs_batch,
                                                          self._key)
            else:
                self._key, sub = jax.random.split(self._key)
                actions, logps, values = self.policy.compute_actions(
                    obs_batch, sub)
            for i in range(N):
                act = actions[i]
                act_env = (int(act) if self.policy.discrete
                           else np.asarray(act))
                if self.action_connectors.connectors:
                    act_env = self.action_connectors(act_env)
                nxt, reward, terminated, truncated, _ =                     self._vec_envs[i].step(act_env)
                rows = per_env_rows[i]
                rows[SampleBatch.OBS].append(obs_batch[i])
                rows[SampleBatch.NEXT_OBS].append(np.asarray(
                    self._vec_obs_conn[i].apply_readonly(nxt)))
                rows[SampleBatch.ACTIONS].append(act)
                rows[SampleBatch.REWARDS].append(np.float32(reward))
                rows[SampleBatch.TERMINATEDS].append(
                    np.float32(terminated))
                rows[SampleBatch.TRUNCATEDS].append(
                    np.float32(truncated))
                rows[SampleBatch.ACTION_LOGP].append(logps[i])
                rows[SampleBatch.VF_PREDS].append(values[i])
                rows[SampleBatch.EPS_ID].append(self._vec_eps[i])
                self._vec_ep_reward[i] += float(reward)
                self._vec_ep_len[i] += 1
                if terminated or truncated:
                    self.completed_rewards.append(
                        self._vec_ep_reward[i])
                    self.completed_lengths.append(self._vec_ep_len[i])
                    self._vec_ep_reward[i] = 0.0
                    self._vec_ep_len[i] = 0
                    self._vec_eps[i] = self._eps_id
                    self._eps_id += 1
                    self._vec_obs[i], _ = self._vec_envs[i].reset()
                else:
                    self._vec_obs[i] = nxt
        batches = []
        for i in range(N):
            batch = SampleBatch(per_env_rows[i])
            batches.append(self._postprocess(
                batch, bootstrap_obs_raw=self._vec_obs[i],
                obs_conn=self._vec_obs_conn[i]))
        out = SampleBatch.concat_samples(batches)
        if self._writer is not None:
            self._writer.write(out)
        return out

    def _postprocess(self, batch: SampleBatch,
                     bootstrap_obs_raw=None,
                     obs_conn=None) -> SampleBatch:
        if not getattr(self.policy, "needs_gae", True):
            return batch
        if bootstrap_obs_raw is None:
            bootstrap_obs_raw = self._obs
        if obs_conn is None:
            obs_conn = self.obs_connectors
        # GAE per episode fragment; bootstrap truncated/continuing tails.
        fragments = []
        for frag in batch.split_by_episode():
            last_terminated = frag[SampleBatch.TERMINATEDS][-1] > 0
            if last_terminated:
                last_value = 0.0
            else:
                bootstrap_obs = np.asarray(
                    obs_conn.apply_readonly(bootstrap_obs_raw))
                last_value = float(self.policy.compute_values(
                    bootstrap_obs[None])[0])
            fragments.append(compute_gae(frag, self.gamma, self.lam,
                                         last_value))
        return SampleBatch.concat_samples(fragments)

    def episode_stats(self, window: int = 100) -> Dict[str, float]:
        rewards = self.completed_rewards[-window:]
        lengths = self.completed_lengths[-window:]
        return {
            "episodes": len(self.completed_rewards),
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "episode_len_mean": float(np.mean(lengths)) if lengths
            else float("nan"),
        }
