"""MultiAgentRolloutWorker: joint-episode sampling over a MultiAgentEnv.

Analog of the reference's multi-agent sampling path (rollout_worker.py +
sampler.py with a policy map): one env hosting several agents, each
mapped to a policy by ``policy_mapping_fn``; every joint step routes each
present agent's observation through its policy, and completed per-agent
trajectories are GAE-postprocessed against that policy's value head and
appended to the policy's batch. sample() returns a MultiAgentBatch.
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import numpy as np

from ray_tpu.rllib.policy import make_policy
from ray_tpu.rllib.policy.jax_policy import compute_gae
from ray_tpu.rllib.policy.sample_batch import MultiAgentBatch, SampleBatch

_ROW_KEYS = (SampleBatch.OBS, SampleBatch.NEXT_OBS, SampleBatch.ACTIONS,
             SampleBatch.REWARDS, SampleBatch.TERMINATEDS,
             SampleBatch.TRUNCATEDS, SampleBatch.ACTION_LOGP,
             SampleBatch.VF_PREDS, SampleBatch.EPS_ID)


def resolve_policy_specs(policies: Dict[str, Any],
                         policy_mapping_fn: Callable[[str], str],
                         env) -> Dict[str, tuple]:
    """Fill in None policy specs from the env's per-agent spaces (the
    first mapped agent defines the spaces, as in the reference)."""
    resolved = {}
    for agent_id in sorted(env.agent_ids):
        pid = policy_mapping_fn(agent_id)
        if pid not in policies:
            raise ValueError(
                f"policy_mapping_fn({agent_id!r}) -> {pid!r}, which is not "
                f"in config.policies {sorted(policies)}")
        if pid not in resolved:
            spec = policies[pid]
            if spec is None:
                spec = (env.observation_space_for(agent_id),
                        env.action_space_for(agent_id))
            resolved[pid] = tuple(spec)
    missing = set(policies) - set(resolved)
    if missing:
        raise ValueError(
            f"Policies {sorted(missing)} are not reachable from any agent "
            "via policy_mapping_fn")
    return resolved


class MultiAgentRolloutWorker:
    def __init__(self, env_creator: Callable, policy_config: Dict[str, Any],
                 worker_index: int = 0, seed: int = 0):
        from ray_tpu.rllib.evaluation.rollout_worker import \
            _pin_rollout_backend
        _pin_rollout_backend(policy_config.get("rollout_backend", "cpu"))
        import jax
        self.env = env_creator(policy_config.get("env_config") or {})
        policies = policy_config["policies"]
        self.policy_mapping_fn = policy_config["policy_mapping_fn"]
        specs = resolve_policy_specs(policies, self.policy_mapping_fn,
                                     self.env)
        from ray_tpu.rllib.connectors import get_connectors
        self.policies = {}
        self.obs_connectors = {}
        self.action_connectors = {}
        self._writers = {}
        output_dir = policy_config.get("output")
        for i, (pid, (obs_space, act_space)) in enumerate(
                sorted(specs.items())):
            # 1000× spacing decorrelates (worker, policy) pairs — plain
            # seed + worker_index + i would give (w=1, i=1) and (w=2, i=0)
            # identical PRNG streams (mirrors the _eps_id spacing below).
            self.policies[pid] = make_policy(
                policy_config, obs_space, act_space,
                seed=seed + 1000 * worker_index + i)
            # Per-policy connector pipelines (stateful filters like
            # MeanStd must track each policy's own observation stream).
            self.obs_connectors[pid], self.action_connectors[pid] = \
                get_connectors(policy_config, obs_space, act_space)
            if output_dir:
                import os

                from ray_tpu.rllib.offline.json_writer import JsonWriter
                self._writers[pid] = JsonWriter(
                    os.path.join(output_dir, pid),
                    worker_index=worker_index)
        self.gamma = policy_config.get("gamma", 0.99)
        self.lam = policy_config.get("lambda", 0.95)
        self.worker_index = worker_index
        self._key = jax.random.PRNGKey(2000 + seed + worker_index)
        self._eps_id = worker_index * 1_000_000
        self._obs, _ = self.env.reset(seed=seed + worker_index)
        # In-progress per-agent trajectories for the current episode.
        self._trajectories: Dict[str, Dict[str, list]] = {}
        self._episode_reward = 0.0
        self._episode_len = 0
        self.completed_rewards: list = []
        self.completed_lengths: list = []

    # -- weights ---------------------------------------------------------

    def set_weights(self, weights: Dict[str, Any]) -> bool:
        for pid, w in weights.items():
            self.policies[pid].set_weights(w)
        return True

    def get_weights(self) -> Dict[str, Any]:
        return {pid: p.get_weights() for pid, p in self.policies.items()}

    # -- sampling --------------------------------------------------------

    def _traj(self, agent_id: str) -> Dict[str, list]:
        traj = self._trajectories.get(agent_id)
        if traj is None:
            traj = self._trajectories[agent_id] = {k: [] for k in _ROW_KEYS}
        return traj

    def _flush_agent(self, agent_id: str, builders: Dict[str, list],
                     terminated: bool) -> None:
        """Close an agent trajectory: GAE against the agent's policy
        (bootstrapping non-terminal tails) and hand it to the policy's
        batch builder."""
        traj = self._trajectories.pop(agent_id, None)
        if not traj or not traj[SampleBatch.OBS]:
            return
        pid = self.policy_mapping_fn(agent_id)
        policy = self.policies[pid]
        batch = SampleBatch({k: np.asarray(v) for k, v in traj.items()})
        last_value = 0.0
        if not terminated:
            last_obs = batch[SampleBatch.NEXT_OBS][-1]
            last_value = float(policy.compute_values(
                np.asarray(last_obs, np.float32)[None])[0])
        batch = compute_gae(batch, self.gamma, self.lam, last_value)
        builders.setdefault(pid, []).append(batch)

    def sample(self, num_steps: int) -> MultiAgentBatch:
        import jax
        builders: Dict[str, list] = {}
        for _ in range(num_steps):
            actions: Dict[str, Any] = {}
            step_meta: Dict[str, tuple] = {}
            for agent_id, obs in self._obs.items():
                pid = self.policy_mapping_fn(agent_id)
                policy = self.policies[pid]
                obs_arr = np.asarray(self.obs_connectors[pid](obs),
                                     np.float32)
                self._key, sub = jax.random.split(self._key)
                action, logp, value = policy.compute_actions(
                    obs_arr[None], sub)
                act = action[0]
                act_env = (int(act) if policy.discrete
                           else np.asarray(act))
                if self.action_connectors[pid].connectors:
                    act_env = self.action_connectors[pid](act_env)
                actions[agent_id] = act_env
                step_meta[agent_id] = (obs_arr, act, logp[0], value[0])
            nxt, rewards, terminateds, truncateds, _ = self.env.step(
                actions)
            term_all = bool(terminateds.get("__all__", False))
            done_all = bool(term_all or truncateds.get("__all__", False))
            for agent_id, (obs_arr, act, logp, value) in step_meta.items():
                traj = self._traj(agent_id)
                term = bool(terminateds.get(agent_id, False))
                trunc = bool(truncateds.get(agent_id, False))
                reward = float(rewards.get(agent_id, 0.0))
                pid = self.policy_mapping_fn(agent_id)
                traj[SampleBatch.OBS].append(obs_arr)
                next_raw = nxt.get(agent_id, obs_arr)
                traj[SampleBatch.NEXT_OBS].append(np.asarray(
                    self.obs_connectors[pid].apply_readonly(next_raw),
                    np.float32))
                traj[SampleBatch.ACTIONS].append(act)
                traj[SampleBatch.REWARDS].append(np.float32(reward))
                traj[SampleBatch.TERMINATEDS].append(np.float32(term))
                traj[SampleBatch.TRUNCATEDS].append(np.float32(trunc))
                traj[SampleBatch.ACTION_LOGP].append(logp)
                traj[SampleBatch.VF_PREDS].append(value)
                traj[SampleBatch.EPS_ID].append(self._eps_id)
                self._episode_reward += reward
                if term or trunc or done_all:
                    # terminateds['__all__'] without a per-agent flag is a
                    # genuine terminal for every agent (the MultiAgentEnv
                    # contract: '__all__' ends the episode for everyone) —
                    # bootstrapping gamma*V(last_obs) there would bias GAE
                    # targets. Truncation ('__all__' in truncateds, or a
                    # per-agent trunc) still bootstraps.
                    self._flush_agent(
                        agent_id, builders,
                        terminated=term or (term_all and not trunc))
            self._episode_len += 1
            if done_all:
                for agent_id in list(self._trajectories):
                    self._flush_agent(agent_id, builders,
                                      terminated=term_all)
                self.completed_rewards.append(self._episode_reward)
                self.completed_lengths.append(self._episode_len)
                self._episode_reward = 0.0
                self._episode_len = 0
                self._eps_id += 1
                self._obs, _ = self.env.reset()
            else:
                self._obs = nxt
        # Fragment boundary: flush alive agents with bootstrapped tails so
        # the learner sees complete GAE fields every round.
        for agent_id in list(self._trajectories):
            self._flush_agent(agent_id, builders, terminated=False)
        policy_batches = {pid: SampleBatch.concat_samples(parts)
                          for pid, parts in builders.items()}
        for pid, writer in self._writers.items():
            if pid in policy_batches:
                writer.write(policy_batches[pid])
        return MultiAgentBatch(policy_batches, env_steps=num_steps)

    def episode_stats(self, window: int = 100) -> Dict[str, float]:
        rewards = self.completed_rewards[-window:]
        lengths = self.completed_lengths[-window:]
        return {
            "episodes": len(self.completed_rewards),
            "episode_reward_mean": float(np.mean(rewards)) if rewards
            else float("nan"),
            "episode_len_mean": float(np.mean(lengths)) if lengths
            else float("nan"),
        }
