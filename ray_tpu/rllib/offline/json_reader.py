"""JsonReader: stream SampleBatches back from JsonWriter output.

Analog of the reference's rllib/offline/json_reader.py: iterates the
``*.json`` files under a directory in round-robin, decoding one batch per
line; ``next()`` cycles forever (offline algorithms sample repeatedly)."""

from __future__ import annotations

import base64
import glob
import json
import os
from typing import List, Optional

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _decode_array(spec) -> np.ndarray:
    arr = np.frombuffer(base64.b64decode(spec["data"]),
                        dtype=np.dtype(spec["dtype"]))
    return arr.reshape(spec["shape"]).copy()


class JsonReader:
    def __init__(self, path: str):
        if os.path.isdir(path):
            self.files: List[str] = sorted(
                glob.glob(os.path.join(path, "*.json")))
        else:
            self.files = sorted(glob.glob(path))
        if not self.files:
            raise FileNotFoundError(f"No offline JSON files under {path!r}")
        self._file_idx = 0
        self._lines: Optional[List[str]] = None
        self._line_idx = 0

    def _load_current(self) -> None:
        with open(self.files[self._file_idx]) as f:
            self._lines = [ln for ln in f if ln.strip()]
        self._line_idx = 0

    def next(self) -> SampleBatch:
        if self._lines is None:
            self._load_current()
        while self._line_idx >= len(self._lines):
            self._file_idx = (self._file_idx + 1) % len(self.files)
            self._load_current()
        row = json.loads(self._lines[self._line_idx])
        self._line_idx += 1
        return SampleBatch({k: _decode_array(v) for k, v in row.items()})

    def next_batch(self, batch_size: int, transform=None) -> SampleBatch:
        """Accumulate fragments into an *exact*-size batch: one jitted
        shape for the consumer, no rows dropped — the remainder carries
        over to the next call. ``transform`` (optional) enriches each
        fragment as it is read (e.g. MARWIL attaching return columns).
        Shared by the offline learners (BC, MARWIL)."""
        carry = getattr(self, "_carry", None)
        while carry is None or len(carry) < batch_size:
            fragment = self.next()
            if transform is not None:
                fragment = transform(fragment)
            carry = (fragment if carry is None else
                     SampleBatch.concat_samples([carry, fragment]))
        out = carry.slice(0, batch_size)
        self._carry = carry.slice(batch_size, len(carry))
        return out

    def read_all(self) -> SampleBatch:
        """Concatenate every batch in every file (for small datasets)."""
        batches = []
        for fname in self.files:
            with open(fname) as f:
                for ln in f:
                    if ln.strip():
                        row = json.loads(ln)
                        batches.append(SampleBatch(
                            {k: _decode_array(v) for k, v in row.items()}))
        return SampleBatch.concat_samples(batches)
