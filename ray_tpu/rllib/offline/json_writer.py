"""JsonWriter: append SampleBatches to newline-delimited JSON files.

Analog of the reference's rllib/offline/json_writer.py: each line is one
batch with base64-encoded numpy columns, so offline data written by rollout
workers round-trips exactly through JsonReader.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Any, Dict

import numpy as np

from ray_tpu.rllib.policy.sample_batch import SampleBatch


def _encode_array(arr: np.ndarray) -> Dict[str, Any]:
    return {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "data": base64.b64encode(np.ascontiguousarray(arr)).decode()}


class JsonWriter:
    def __init__(self, path: str, worker_index: int = 0,
                 max_file_size: int = 64 * 1024 * 1024):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_file_size = max_file_size
        self._index = 0
        self._worker_index = worker_index
        self._file = None

    def _rotate(self):
        if self._file is not None:
            self._file.close()
        fname = os.path.join(
            self.path,
            f"output-worker{self._worker_index}-{self._index:05d}.json")
        self._index += 1
        self._file = open(fname, "a")

    def write(self, batch: SampleBatch) -> None:
        if self._file is None or self._file.tell() > self.max_file_size:
            self._rotate()
        row = {k: _encode_array(np.asarray(v)) for k, v in batch.items()}
        self._file.write(json.dumps(row) + "\n")
        self._file.flush()

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
