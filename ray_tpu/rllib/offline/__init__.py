from ray_tpu.rllib.offline.json_reader import JsonReader
from ray_tpu.rllib.offline.json_writer import JsonWriter

__all__ = ["JsonReader", "JsonWriter"]
