"""Resilient session channel: acked frames, resend ring, reconnect.

A :class:`ResilientChannel` wraps one TCP socket of a head<->daemon
session. Every outbound frame is wrapped in a wire-protocol v7 seq
envelope (``wire.wrap_seq``) carrying a monotonic per-session sequence
number plus a cumulative ack of the highest inbound sequence seen, and
is held in a bounded resend ring until the peer acks it. Acks piggyback
on regular traffic; after ``ack_every`` unacked inbound frames an ack
becomes *pending* and is carried by the next outbound frame or, if none
goes out within ``ack_flush_ms``, flushed as a pure ack (seq 0) by a
background timer — the receive path itself never writes, so
one-directional streams still prune the peer's ring without a
synchronous send under the recv lock.

Sends are zero-copy: :meth:`ResilientChannel.send_parts` packs the
length prefix + seq envelope into a small reusable header buffer and
hands caller buffers straight to ``socket.sendmsg`` scatter-gather
(:func:`sock_send_parts`). The resend ring joins frames at or below
``SENDMSG_THRESHOLD`` bytes into one snapshot; above it, parts that are
provably immutable (``bytes``) are kept by reference while mutable
parts (bytearrays, pickle-5 OOB views over live array memory) are
snapshotted — so callers may reuse or mutate their buffers the moment
``send_parts`` returns, and a replay after a reconnect is always
byte-identical to the original send.

When a send or recv hits a transient transport error the channel closes
the socket, flips to ``broken``, and raises :class:`ChannelBroken`; the
frame that failed is already in the ring. The daemon side then re-dials
the head with backoff+jitter and a ``resume`` handshake inside
``RAY_TPU_CHANNEL_RECONNECT_WINDOW_S``; both sides :meth:`attach` the
fresh socket and replay only the frames past the peer's last-seen
sequence. Receivers drop ``seq <= in_seq`` duplicates, giving
exactly-once delivery in order. Node death fires only after the window
is exhausted (:meth:`wait_recovered` closes the channel) or the daemon
is confirmed gone via the health channel.
"""

from __future__ import annotations

import collections
import errno
import logging
import os
import random
import socket
import struct
import threading
import time
from typing import List, Optional, Tuple

from ray_tpu._private import chaos
from ray_tpu._private import wire as _wire

logger = logging.getLogger(__name__)

# Emit a pure ack after this many unacked inbound frames (keeps the
# peer's resend ring pruned under one-directional traffic). Default for
# the `channel_ack_every` config flag; the ack is deferred — piggybacked
# on the next outbound frame or flushed by a timer after
# `channel_ack_flush_ms` — never written synchronously from recv.
ACK_EVERY = 32
ACK_FLUSH_MS = 20

# Frames whose payload totals at or below this many bytes are sent as
# one joined buffer (`sendall`) and SNAPSHOTTED into the resend ring —
# one small memcpy beats sendmsg iovec setup. Larger frames go
# scatter-gather with zero payload copies on the wire; the ring keeps
# immutable `bytes` parts by reference and snapshots everything else
# (see ResilientChannel.send_parts).
SENDMSG_THRESHOLD = int(
    os.environ.get("RAY_TPU_CHANNEL_SENDMSG_THRESHOLD", 65536))

# POSIX guarantees at least 16 iovecs; Linux allows 1024. Batches with
# more parts are written in successive sendmsg calls.
_IOV_MAX = 1024

_LEN = struct.Struct(">Q")
_MAX_FRAME = 1 << 34

_BUFFER_TYPES = (bytes, bytearray, memoryview)


def _buf_len(p) -> int:
    """Byte length of one buffer part. len() of a memoryview counts
    ELEMENTS, not bytes — a non-'B'-format view (a float array's view)
    would corrupt length prefixes and ring accounting."""
    return p.nbytes if isinstance(p, memoryview) else len(p)


def _nbytes(payload) -> int:
    """Byte length of a ring entry: one buffer or a tuple of parts."""
    if isinstance(payload, _BUFFER_TYPES):
        return _buf_len(payload)
    return sum(_buf_len(p) for p in payload)


def _ring_stable(p) -> bool:
    """True when the resend ring may hold ``p`` by reference: the
    bytes are provably immutable (`bytes`, or a view whose exporting
    object is `bytes`). Anything else — a bytearray, a pickle-5 OOB
    view over an actor's live array — can be mutated by its owner
    after send_parts returns, and a ringed reference would replay the
    MUTATED bytes after a reconnect (exactly-once delivery of wrong
    data); such parts are snapshotted into the ring instead."""
    return isinstance(p, bytes) or (
        isinstance(p, memoryview) and isinstance(p.obj, bytes))


def sock_send_parts(sock, parts, *, threshold: Optional[int] = None) -> int:
    """Write a sequence of buffers to ``sock`` without joining them.

    At or below ``threshold`` total bytes (or when the socket lacks
    ``sendmsg``) the parts are joined once and written with ``sendall``
    — for small frames one memcpy is cheaper than iovec setup. Above it
    the buffers are handed to the kernel via scatter-gather
    ``sendmsg``, advancing past partial writes with memoryview slices:
    payload bytes are never copied in userspace. Returns the total byte
    count written."""
    total = sum(_buf_len(p) for p in parts)
    if threshold is None:
        threshold = SENDMSG_THRESHOLD
    sendmsg = getattr(sock, "sendmsg", None)
    if sendmsg is None or total <= threshold:
        sock.sendall(b"".join(parts))
        return total
    views = [memoryview(p).cast("B") for p in parts if _buf_len(p)]
    idx, n = 0, len(views)
    while idx < n:
        sent = sendmsg(views[idx:idx + _IOV_MAX])
        while sent > 0:
            v = views[idx]
            if sent >= len(v):
                sent -= len(v)
                idx += 1
            else:
                views[idx] = v[sent:]
                sent = 0
    return total


class ChannelBroken(ConnectionError):
    """Transient transport failure; unacked frames are preserved in the
    resend ring and replayed by the next :meth:`ResilientChannel.attach`."""


class ChannelClosed(ConnectionError):
    """Channel permanently closed; no recovery will happen."""


def is_transient(exc: BaseException) -> bool:
    """Classify an exception from a socket op as a transient transport
    error (worth a reconnect/retry) rather than a programming error."""
    return isinstance(exc, (OSError, struct.error, EOFError))


def connection_refused(exc: BaseException) -> bool:
    """True when a dial failed because NOTHING is listening (RST on
    connect). For a session resume this is decisive: the head process
    is gone, its channel ring died with it, and no amount of in-window
    retrying can ever resume — the caller should fall through to the
    full re-register/re-dial path (which a REBORN head can answer)."""
    if isinstance(exc, ConnectionRefusedError):
        return True
    return isinstance(exc, OSError) and exc.errno in (
        errno.ECONNREFUSED, errno.ECONNABORTED)


class Backoff:
    """Exponential backoff with jitter (anti-thundering-herd).

    ``next()`` returns a delay drawn uniformly from [base/2, base],
    with base doubling from ``initial`` up to ``cap``. Pass a seeded
    ``rng`` for deterministic tests.
    """

    def __init__(self, initial: float = 0.2, cap: float = 2.0, rng=None):
        self._initial = float(initial)
        self._cap = float(cap)
        self._rng = rng if rng is not None else random.Random()
        self._attempt = 0

    def next(self) -> float:
        base = min(self._cap, self._initial * (2.0 ** self._attempt))
        self._attempt += 1
        return base * (0.5 + 0.5 * self._rng.random())

    def sleep(self) -> float:
        delay = self.next()
        time.sleep(delay)
        return delay

    def reset(self) -> None:
        self._attempt = 0


def close_socket(sock) -> None:
    """shutdown+close, quietly (shutdown pops any blocked reader)."""
    try:
        sock.shutdown(socket.SHUT_RDWR)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionError("peer closed connection")
        got += r
    return bytes(buf)


def recv_raw_frame(sock) -> bytes:
    """Read one length-prefixed frame (same framing as multinode)."""
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > _MAX_FRAME:
        raise ConnectionError(f"oversized frame: {length} bytes")
    return _recv_exact(sock, length)


class _ResendRing:
    """Bounded byte-budget ring of unacked outbound frames.

    Overflow evicts oldest-first and records the eviction point; a
    resume from a peer that had not acked past it is refused (the
    channel can no longer replay losslessly → node death, exactly the
    pre-channel behaviour)."""

    def __init__(self, cap_bytes: int):
        self._frames: collections.deque = collections.deque()
        self._bytes = 0
        self.cap_bytes = int(cap_bytes)
        self.evicted_to = 0

    def append(self, seq: int, payload) -> None:
        """``payload`` is one buffer (joined small frame) or a tuple of
        parts (large frame — immutable `bytes` by reference, mutable
        parts already snapshotted by send_parts; accounted by summed
        part byte length)."""
        self._frames.append((seq, payload))
        self._bytes += _nbytes(payload)
        # Keep at least the newest frame even if it alone beats the
        # budget, so a single oversized frame can still be replayed.
        while self._bytes > self.cap_bytes and len(self._frames) > 1:
            old_seq, old_payload = self._frames.popleft()
            self._bytes -= _nbytes(old_payload)
            self.evicted_to = old_seq

    def prune(self, acked_seq: int) -> None:
        while self._frames and self._frames[0][0] <= acked_seq:
            _, payload = self._frames.popleft()
            self._bytes -= _nbytes(payload)

    def can_resume_from(self, peer_last_seq: int) -> bool:
        return peer_last_seq >= self.evicted_to

    def frames_after(self, peer_last_seq: int) -> List[Tuple[int, object]]:
        return [(s, p) for s, p in self._frames if s > peer_last_seq]

    def __len__(self) -> int:
        return len(self._frames)

    @property
    def nbytes(self) -> int:
        return self._bytes


class ResilientChannel:
    """One side of a resumable head<->daemon session channel."""

    def __init__(self, sock, *, site: str, ring_bytes: int,
                 window_s: float, ack_every: Optional[int] = None,
                 ack_flush_ms: Optional[int] = None):
        self._cv = threading.Condition(threading.Lock())
        self._sock = sock
        self._site = site
        self._ring = _ResendRing(ring_bytes)
        self.window_s = float(window_s)
        self.ack_every = int(
            ack_every if ack_every is not None
            else os.environ.get("RAY_TPU_CHANNEL_ACK_EVERY", ACK_EVERY))
        self.ack_flush_ms = int(
            ack_flush_ms if ack_flush_ms is not None
            else os.environ.get("RAY_TPU_CHANNEL_ACK_FLUSH_MS",
                                ACK_FLUSH_MS))
        self.out_seq = 0
        self.in_seq = 0
        self._acked_in = 0
        # v9 membership fencing: the session incarnation's node_epoch,
        # stamped into every outbound seq envelope once the owner learns
        # it (head: at registration, before the ack is sent; daemon: from
        # the registered ack). 0 = not yet learned — pre-registration
        # frames are never fenced. An inbound enveloped frame stamped
        # with a DIFFERENT non-zero epoch is from another incarnation of
        # this session: dropped and counted, never applied.
        self.epoch = 0
        # Reused header buffer: length prefix + seq envelope, packed in
        # place under self._cv for every write (no per-frame allocation,
        # no prepend copy).
        self._hdr = bytearray(_LEN.size + _wire.SEQ_SIZE)
        self._ack_pending = False
        self._ack_thread: Optional[threading.Thread] = None
        self.broken = False
        self.closed = False
        self.broken_at: Optional[float] = None
        self.generation = 0
        self.reconnects = 0

    # ------------------------------------------------------------- send
    def send_frame(self, payload) -> None:
        """Ring-then-send for a single pre-joined payload buffer."""
        self.send_parts(payload if isinstance(payload, bytes)
                        else bytes(payload))

    def send_parts(self, *parts) -> None:
        """Ring-then-send, zero-copy: the frame is sequenced and
        ring-buffered before the socket write, so a failed write
        (ChannelBroken) is still replayed by the next attach — callers
        never resend.

        Ownership rule: callers may reuse or mutate their buffers as
        soon as this returns. Frames totaling <= SENDMSG_THRESHOLD
        bytes are joined into one ring snapshot; above it the first
        write scatter-gathers the CALLER'S buffers (zero payload
        copies on the hot path) while the ring keeps immutable `bytes`
        parts by reference and snapshots mutable parts — a reconnect
        replay therefore always carries the bytes as they were at send
        time, never a later mutation."""
        with self._cv:
            if self.closed:
                raise ChannelClosed("channel closed")
            self.out_seq += 1
            seq = self.out_seq
            if _nbytes(parts) <= SENDMSG_THRESHOLD:
                entry = b"".join(parts)
            else:
                entry = tuple(p if _ring_stable(p) else bytes(p)
                              for p in parts)
            self._ring.append(seq, entry)
            if self.broken:
                raise ChannelBroken("channel broken (frame held for replay)")
            self._write_locked(seq, parts)

    def _write_locked(self, seq: int, payload) -> None:
        sock = self._sock
        parts = ((payload,) if isinstance(payload, _BUFFER_TYPES)
                 else tuple(payload))
        body = _nbytes(parts)
        hdr = self._hdr  # safe to reuse: all writes run under self._cv
        _LEN.pack_into(hdr, 0, _wire.SEQ_SIZE + body)
        _wire.pack_seq_into(hdr, _LEN.size, seq, self.in_seq, self.epoch)
        self._acked_in = self.in_seq
        self._ack_pending = False
        try:
            if chaos.ACTIVE:
                chaos.maybe_inject(self._site + ".send", sock)
            sock_send_parts(sock, (hdr,) + parts)
        except Exception as exc:
            if not is_transient(exc):
                raise
            self._mark_broken_locked(sock, exc)
            self._count("channel_send_retries")
            raise ChannelBroken(f"send failed: {exc}") from exc
        self._record_sent(len(hdr) + body, seq == 0)

    # ------------------------------------------------------------- recv
    def recv_frame(self) -> bytes:
        """Return the next inbound payload, transparently consuming pure
        acks and dropping replayed duplicates (exactly-once)."""
        while True:
            with self._cv:
                if self.closed:
                    raise ChannelClosed("channel closed")
                if self.broken:
                    raise ChannelBroken("channel broken")
                sock = self._sock
                gen = self.generation
            try:
                if chaos.ACTIVE:
                    chaos.maybe_inject(self._site + ".recv", sock)
                raw = recv_raw_frame(sock)
            except Exception as exc:
                if not is_transient(exc):
                    raise
                with self._cv:
                    if self.closed:
                        raise ChannelClosed("channel closed") from exc
                    if gen != self.generation:
                        continue  # re-attached under us: read the new sock
                    self._mark_broken_locked(sock, exc)
                raise ChannelBroken(f"recv failed: {exc}") from exc
            unwrapped = _wire.unwrap_seq(raw)
            if unwrapped is None:
                return raw  # raw handshake frame: pass through
            seq, ack, epoch, inner = unwrapped
            if self.epoch and epoch and epoch != self.epoch:
                # Stale incarnation (v9 fencing): a frame from a
                # previous life of this session must never be applied —
                # its ack must not prune our ring either (the acked
                # state belongs to the dead incarnation).
                self._count("frames_fenced")
                continue
            with self._cv:
                self._ring.prune(ack)
                if seq == 0:
                    continue  # pure ack
                if seq <= self.in_seq:
                    continue  # duplicate from a replay
                self.in_seq = seq
                if (self.in_seq - self._acked_in >= self.ack_every
                        and not self.broken and not self.closed):
                    # Deferred: piggybacks on the next outbound frame,
                    # or the flusher writes a pure ack after
                    # ack_flush_ms. Never a synchronous write here.
                    self._schedule_ack_locked()
            return inner

    def _schedule_ack_locked(self) -> None:
        if self._ack_pending:
            return
        self._ack_pending = True
        t = self._ack_thread
        if t is None or not t.is_alive():
            t = threading.Thread(target=self._ack_flush_loop,
                                 name=f"chan-ack-{self._site}",
                                 daemon=True)
            self._ack_thread = t
            t.start()
        else:
            self._cv.notify_all()

    def _ack_flush_loop(self) -> None:
        """Flush deferred pure acks that no outbound frame piggybacked
        within the flush interval. A failed flush goes through
        _write_locked, which marks the channel broken exactly once and
        counts it in channel_send_retries — never swallowed silently."""
        while True:
            with self._cv:
                # A broken channel parks here (attach notifies) rather
                # than waking every ack_flush_ms to skip the flush for
                # the whole reconnect window.
                while not ((self._ack_pending and not self.broken)
                           or self.closed):
                    self._cv.wait(1.0)
                if self.closed:
                    return
            time.sleep(self.ack_flush_ms / 1000.0)  # piggyback grace
            with self._cv:
                if self.closed:
                    return
                if self._ack_pending and not self.broken:
                    try:
                        self._write_locked(0, b"")
                    except ChannelBroken:
                        pass  # marked broken + counted by _write_locked

    # ------------------------------------------------------- transitions
    def _mark_broken_locked(self, sock, exc=None) -> None:
        if self.closed or self.broken or sock is not self._sock:
            return
        self.broken = True
        self.broken_at = time.monotonic()
        close_socket(sock)
        self._cv.notify_all()
        logger.info("channel[%s] broken: %s", self._site, exc)

    def wait_recovered(self) -> bool:
        """Park until the channel is re-attached (True) or closed /
        window exhausted (False). Exhaustion closes the channel."""
        with self._cv:
            while True:
                if self.closed:
                    return False
                if not self.broken:
                    return True
                deadline = ((self.broken_at or time.monotonic())
                            + self.window_s)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    logger.warning(
                        "channel[%s] reconnect window (%.1fs) exhausted",
                        self._site, self.window_s)
                    self._close_locked()
                    return False
                self._cv.wait(min(remaining, 0.5))

    def attach(self, sock, peer_last_seq: int) -> bool:
        """Adopt a fresh socket after a resume handshake, replaying
        unacked frames past ``peer_last_seq``. False if the ring can no
        longer replay losslessly or the channel is closed."""
        peer_last_seq = int(peer_last_seq)
        with self._cv:
            if self.closed:
                return False
            self._ring.prune(peer_last_seq)
            if not self._ring.can_resume_from(peer_last_seq):
                logger.warning(
                    "channel[%s] resume refused: ring evicted past peer "
                    "seq %d", self._site, peer_last_seq)
                return False
            old, self._sock = self._sock, sock
            self.generation += 1
            self.broken = False
            self.broken_at = None
            self.reconnects += 1
            replay = self._ring.frames_after(peer_last_seq)
            self._count("channel_reconnects")
            if replay:
                self._count("channel_frames_resent", len(replay))
            try:
                from ray_tpu._private import events
                events.emit(
                    "channel",
                    f"channel[{self._site}] resumed (gen "
                    f"{self.generation}, {len(replay)} frame(s) replayed)",
                    severity="warning",
                    labels={"site": self._site,
                            "frames_replayed": len(replay)})
            except Exception:  # noqa: BLE001 - journal never breaks resume
                pass
            self._cv.notify_all()
            if old is not sock:
                close_socket(old)
            logger.info("channel[%s] resumed (gen %d, %d frame(s) replayed)",
                        self._site, self.generation, len(replay))
            try:
                for seq, payload in replay:
                    self._write_locked(seq, payload)
            except ChannelBroken:
                pass  # broke again mid-replay; the next attach retries
            return True

    def close(self) -> None:
        with self._cv:
            self._close_locked()

    def _close_locked(self) -> None:
        if self.closed:
            return
        self.closed = True
        close_socket(self._sock)
        self._cv.notify_all()

    # ---------------------------------------------------------- helpers
    def unacked(self) -> int:
        with self._cv:
            return len(self._ring)

    @staticmethod
    def _count(name: str, n: int = 1) -> None:
        try:
            from ray_tpu._private import builtin_metrics
            getattr(builtin_metrics, name)().inc(n)
        except Exception:  # metrics must never break transport recovery
            pass

    @staticmethod
    def _record_sent(nbytes: int, is_ack: bool) -> None:
        """Hot-path counters via the lock-free fast cells (folded into
        ray_tpu_channel_bytes_sent_total / _acks_sent_total by the
        metrics agent's flush)."""
        global _metrics_mod
        m = _metrics_mod
        if m is None:
            try:
                from ray_tpu._private import builtin_metrics as m
            except Exception:
                return
            _metrics_mod = m
        try:
            m.record_channel_bytes_sent(nbytes)
            if is_ack:
                m.record_channel_ack_sent()
        except Exception:  # metrics must never break transport
            pass


_metrics_mod = None
