"""ObjectRef: a future handle to an object in the store.

Analog of the reference ObjectRef (python/ray/_raylet.pyx ObjectRef): compares
and hashes by ID, picklable (serializing a ref inside a task argument or
return value keeps it a reference — the borrowing protocol; resolution happens
only through ``get``). Supports ``await`` when used inside async actors.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID, TaskID

#: Borrow auto-bind backoff: when binding the client runtime fails (head
#: briefly unreachable), don't re-attempt a blocking connect inside
#: EVERY ref construction — and never fail silently.
_bind_failed_at = 0.0
_BIND_RETRY_S = 5.0


def _is_local_node(node_hex: str) -> bool:
    """True when this process IS (or lives on) the hinted owner node."""
    try:
        from ray_tpu._private import multinode as _mn
        daemon = _mn._current_daemon
        if daemon is not None:
            return daemon.node_id_hex == node_hex
        import os as _os
        return _os.environ.get("RAY_TPU_NODE_ID") == node_hex
    except Exception:  # noqa: BLE001
        return False


def _head_owner_hint(object_id):
    """Owner hint for a node-resident object, looked up when a HEAD
    process pickles the ref (ownership phase 3): the hint travels with
    the ref so any borrower can reach the OWNER's object server for
    location queries, payload fetches, and borrow registration without
    a head round-trip (reference: ObjectRef carries owner_address,
    common.proto ObjectReference.owner_address)."""
    try:
        from ray_tpu._private import worker as _worker
        runtime = getattr(_worker.global_worker, "_runtime", None)
        rv_map = getattr(runtime, "_remote_values", None)
        if rv_map is None:
            return None
        rv = rv_map.get(object_id)
        if rv is None:
            return None
        node_id, key = rv
        conn = runtime._remote_nodes.get(node_id)
        if conn is None or conn.object_addr is None:
            return None
        host, port = conn.object_addr
        return (key, str(host), int(port), node_id.hex())
    except Exception:  # noqa: BLE001 - hints are best-effort
        return None


class ObjectRef:
    __slots__ = ("_id", "_owner_hint", "_registered", "_ownerward",
                 "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint=None):
        self._id = object_id
        self._owner_hint = owner_hint
        # Phase-3 borrow: registered directly with the OWNER daemon
        # (its object server tracks borrowers; bytes survive a head-side
        # free while any borrow is held). The head pin (refs.add_local
        # below) remains the directory-entry refcount.
        self._ownerward = False
        # Ownership bookkeeping (reference: reference_count.h local refs):
        # every live handle holds one local reference; the owner frees the
        # value when the count hits zero.
        self._registered = False
        try:
            from ray_tpu._private import worker as _worker
            runtime = getattr(_worker.global_worker, "_runtime", None)
            if runtime is None and \
                    _worker._client_context_address() is not None:
                # Daemon/worker context with no runtime bound yet:
                # deserializing a ref IS the borrow moment — without
                # binding (and sending ref_add), the creator's session
                # closing would free an object this process still
                # holds (reference: borrower registration on
                # deserialization, reference_count.h borrowed_refs).
                import time as _time
                global _bind_failed_at
                if _time.monotonic() - _bind_failed_at >= _BIND_RETRY_S:
                    try:
                        runtime = _worker.global_worker.runtime
                    except Exception:  # noqa: BLE001 - head unreachable
                        _bind_failed_at = _time.monotonic()
                        import logging
                        logging.getLogger("ray_tpu").warning(
                            "could not bind the client runtime to "
                            "register a borrowed ref %s — its borrow is "
                            "NOT tracked until a later API call binds",
                            object_id.hex()[:16])
            if runtime is not None:
                runtime.refs.add_local(object_id)
                self._registered = True
                if owner_hint is not None and \
                        getattr(runtime, "is_client", False) and \
                        not _is_local_node(owner_hint[3]):
                    # Client context borrowing ANOTHER node's object:
                    # register with the OWNER (async notice over the
                    # process's borrow channel — enqueue only, never a
                    # dial or send on this path). Self-node refs skip:
                    # the creator's head pin already guards them and a
                    # loopback borrow of your own bytes adds nothing.
                    from ray_tpu._private.dataplane import GLOBAL_BORROWS
                    key, host, port, _node = owner_hint
                    GLOBAL_BORROWS.add((host, port), key)
                    self._ownerward = True
        except Exception:  # noqa: BLE001 - never fail handle creation
            pass

    def __del__(self):
        if getattr(self, "_ownerward", False):
            try:
                from ray_tpu._private.dataplane import GLOBAL_BORROWS
                key, host, port, _node = self._owner_hint
                GLOBAL_BORROWS.delete((host, port), key)
            except Exception:  # noqa: BLE001 - interpreter teardown
                pass
        if not getattr(self, "_registered", False):
            return
        try:
            from ray_tpu._private.worker import global_worker
            runtime = getattr(global_worker, "_runtime", None)
            if runtime is not None:
                runtime.on_ref_deleted(self._id)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    # -- identity ---------------------------------------------------------

    def object_id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self) -> TaskID:
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        hint = self._owner_hint
        if hint is None:
            # Head process shipping a node-resident ref: stamp the
            # owner's address so the receiver can go owner-ward.
            hint = _head_owner_hint(self._id)
        return (ObjectRef, (self._id, hint))

    # -- future interface -------------------------------------------------

    def is_ready(self) -> bool:
        from ray_tpu._private.worker import global_worker
        return global_worker.runtime.store.contains(self._id)

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading

        from ray_tpu._private.worker import global_worker

        fut: concurrent.futures.Future = concurrent.futures.Future()
        runtime = global_worker.runtime

        def _wait():
            try:
                fut.set_result(runtime.get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001 - propagate to future
                fut.set_exception(e)

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()
