"""ObjectRef: a future handle to an object in the store.

Analog of the reference ObjectRef (python/ray/_raylet.pyx ObjectRef): compares
and hashes by ID, picklable (serializing a ref inside a task argument or
return value keeps it a reference — the borrowing protocol; resolution happens
only through ``get``). Supports ``await`` when used inside async actors.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.ids import ObjectID, TaskID

#: Borrow auto-bind backoff: when binding the client runtime fails (head
#: briefly unreachable), don't re-attempt a blocking connect inside
#: EVERY ref construction — and never fail silently.
_bind_failed_at = 0.0
_BIND_RETRY_S = 5.0


class ObjectRef:
    __slots__ = ("_id", "_owner_hint", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_hint: Optional[str] = None):
        self._id = object_id
        self._owner_hint = owner_hint
        # Ownership bookkeeping (reference: reference_count.h local refs):
        # every live handle holds one local reference; the owner frees the
        # value when the count hits zero.
        self._registered = False
        try:
            from ray_tpu._private import worker as _worker
            runtime = getattr(_worker.global_worker, "_runtime", None)
            if runtime is None and \
                    _worker._client_context_address() is not None:
                # Daemon/worker context with no runtime bound yet:
                # deserializing a ref IS the borrow moment — without
                # binding (and sending ref_add), the creator's session
                # closing would free an object this process still
                # holds (reference: borrower registration on
                # deserialization, reference_count.h borrowed_refs).
                import time as _time
                global _bind_failed_at
                if _time.monotonic() - _bind_failed_at >= _BIND_RETRY_S:
                    try:
                        runtime = _worker.global_worker.runtime
                    except Exception:  # noqa: BLE001 - head unreachable
                        _bind_failed_at = _time.monotonic()
                        import logging
                        logging.getLogger("ray_tpu").warning(
                            "could not bind the client runtime to "
                            "register a borrowed ref %s — its borrow is "
                            "NOT tracked until a later API call binds",
                            object_id.hex()[:16])
            if runtime is not None:
                runtime.refs.add_local(object_id)
                self._registered = True
        except Exception:  # noqa: BLE001 - never fail handle creation
            pass

    def __del__(self):
        if not getattr(self, "_registered", False):
            return
        try:
            from ray_tpu._private.worker import global_worker
            runtime = getattr(global_worker, "_runtime", None)
            if runtime is not None:
                runtime.on_ref_deleted(self._id)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    # -- identity ---------------------------------------------------------

    def object_id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def task_id(self) -> TaskID:
        return self._id.task_id()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner_hint))

    # -- future interface -------------------------------------------------

    def is_ready(self) -> bool:
        from ray_tpu._private.worker import global_worker
        return global_worker.runtime.store.contains(self._id)

    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        import concurrent.futures
        import threading

        from ray_tpu._private.worker import global_worker

        fut: concurrent.futures.Future = concurrent.futures.Future()
        runtime = global_worker.runtime

        def _wait():
            try:
                fut.set_result(runtime.get([self], timeout=None)[0])
            except BaseException as e:  # noqa: BLE001 - propagate to future
                fut.set_exception(e)

        threading.Thread(target=_wait, daemon=True).start()
        return fut

    def __await__(self):
        import asyncio
        return asyncio.wrap_future(self.future()).__await__()
