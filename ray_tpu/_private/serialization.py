"""Serialization context: cloudpickle with framework-object passthrough.

Analog of the reference's SerializationContext (python/ray/_private/
serialization.py). cloudpickle handles closures/lambdas/dynamic classes;
ObjectRef / ActorHandle define ``__reduce__`` so they travel as IDs (borrow
semantics). Large numpy/jax arrays are serialized out-of-band via pickle5
buffers when the transport supports it; the shared-memory store path (native
C++ store) restores zero-copy.
"""

from __future__ import annotations

import io
import pickle
from typing import Any, List, Tuple

import cloudpickle


class Serializer:
    """Pickles values; collects out-of-band buffers for zero-copy transports."""

    def serialize(self, value: Any) -> bytes:
        return cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def serialize_oob(self, value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
        buffers: List[pickle.PickleBuffer] = []
        payload = cloudpickle.dumps(
            value, protocol=5, buffer_callback=buffers.append)
        return payload, buffers

    def deserialize(self, payload: bytes, buffers=None) -> Any:
        if buffers:
            return pickle.loads(payload, buffers=buffers)
        return pickle.loads(payload)


_serializer = Serializer()


def serialize(value: Any) -> bytes:
    return _serializer.serialize(value)


def deserialize(payload: bytes) -> Any:
    return _serializer.deserialize(payload)


def dumps_function(fn) -> bytes:
    """Pickle a function/class definition for shipping to workers.

    Analog of the reference's function export to GCS KV
    (python/ray/_private/function_manager.py); here the pickled definition is
    cached by the runtime and shipped with the first task that needs it.
    """
    return cloudpickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL)


def loads_function(payload: bytes):
    return pickle.loads(payload)
