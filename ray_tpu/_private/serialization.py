"""Serialization context: cloudpickle with framework-object passthrough.

Analog of the reference's SerializationContext (python/ray/_private/
serialization.py). cloudpickle handles closures/lambdas/dynamic classes;
ObjectRef / ActorHandle define ``__reduce__`` so they travel as IDs (borrow
semantics). Large numpy/jax arrays are serialized out-of-band via pickle5
buffers when the transport supports it; the shared-memory store path (native
C++ store) restores zero-copy.
"""

from __future__ import annotations

import os
import pickle
import struct
from typing import Any, List, Tuple

import cloudpickle

# Out-of-band frame: pickle-5 header plus its raw buffers laid down
# contiguously, so a large numpy/JAX array is written into the store
# with ONE memcpy of the data instead of pickle's full-payload copy.
#
#   magic b"\x0bOB1" | >I nbufs | >Q header_len | nbufs x >Q buffer_len
#   | pickle header | buffers, each preceded by zero padding to the
#   next 64-byte offset (so restored arrays stay cache-line aligned).
#
# The first magic byte 0x0b is not a valid first pickle opcode frame
# byte (protocol-2+ pickles start with 0x80), so ``deserialize`` can
# sniff the format from the payload alone — every existing call site
# keeps working whether the writer framed OOB or not.
_OOB_MAGIC = b"\x0bOB1"
_OOB_HEAD = struct.Struct(">IQ")
_OOB_LEN = struct.Struct(">Q")
_OOB_ALIGN = 64


def _oob_min_bytes() -> int:
    try:
        return int(os.environ.get("RAY_TPU_OOB_MIN_BYTES", "65536"))
    except ValueError:
        return 65536


class Serializer:
    """Pickles values; collects out-of-band buffers for zero-copy transports."""

    def serialize(self, value: Any) -> bytes:
        return cloudpickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def serialize_oob(self, value: Any) -> Tuple[bytes, List[pickle.PickleBuffer]]:
        buffers: List[pickle.PickleBuffer] = []
        payload = cloudpickle.dumps(
            value, protocol=5, buffer_callback=buffers.append)
        return payload, buffers

    def serialize_parts(self, value: Any) -> List[Any]:
        """Serialize into a list of bytes-like parts whose concatenation
        is the stored payload. Values carrying big pickle-5 buffers
        (numpy/JAX arrays) come back as an OOB frame — meta + header +
        the raw buffer views, uncopied — so the store can lay them down
        with a single data memcpy. Everything else (or small buffers,
        or non-contiguous ones) degrades to ``[serialize(value)]``."""
        try:
            header, buffers = self.serialize_oob(value)
        except Exception:
            return [self.serialize(value)]
        if not buffers:
            return [header]
        try:
            raws = [b.raw() for b in buffers]
        except BufferError:
            # Non-contiguous buffer (e.g. a sliced array): plain pickle.
            return [self.serialize(value)]
        total = sum(r.nbytes for r in raws)
        if total < _oob_min_bytes():
            return [self.serialize(value)]
        meta = bytearray(_OOB_MAGIC)
        meta += _OOB_HEAD.pack(len(raws), len(header))
        for r in raws:
            meta += _OOB_LEN.pack(r.nbytes)
        parts: List[Any] = [bytes(meta), header]
        pos = len(meta) + len(header)
        for r in raws:
            pad = (-pos) % _OOB_ALIGN
            if pad:
                parts.append(b"\x00" * pad)
                pos += pad
            parts.append(r)
            pos += r.nbytes
        return parts

    def deserialize(self, payload, buffers=None) -> Any:
        if buffers:
            return pickle.loads(payload, buffers=buffers)
        if (len(payload) >= len(_OOB_MAGIC)
                and bytes(payload[:len(_OOB_MAGIC)]) == _OOB_MAGIC):
            return self._deserialize_oob(memoryview(payload))
        return pickle.loads(payload)

    def _deserialize_oob(self, mv: memoryview) -> Any:
        off = len(_OOB_MAGIC)
        nbufs, hlen = _OOB_HEAD.unpack_from(mv, off)
        off += _OOB_HEAD.size
        lens = [_OOB_LEN.unpack_from(mv, off + i * _OOB_LEN.size)[0]
                for i in range(nbufs)]
        off += nbufs * _OOB_LEN.size
        header = bytes(mv[off:off + hlen])
        off += hlen
        bufs: List[bytes] = []
        for ln in lens:
            off += (-off) % _OOB_ALIGN
            # Copy out of the (possibly pinned/mmap'd) view: restored
            # arrays must outlive the store entry they were read from.
            bufs.append(bytes(mv[off:off + ln]))
            off += ln
        return pickle.loads(header, buffers=bufs)


_serializer = Serializer()


def serialize(value: Any) -> bytes:
    return _serializer.serialize(value)


def serialize_parts(value: Any) -> List[Any]:
    return _serializer.serialize_parts(value)


def deserialize(payload) -> Any:
    return _serializer.deserialize(payload)


def dumps_function(fn) -> bytes:
    """Pickle a function/class definition for shipping to workers.

    Analog of the reference's function export to GCS KV
    (python/ray/_private/function_manager.py); here the pickled definition is
    cached by the runtime and shipped with the first task that needs it.
    """
    return cloudpickle.dumps(fn, protocol=pickle.HIGHEST_PROTOCOL)


def loads_function(payload: bytes):
    return pickle.loads(payload)
