"""Global worker state and the top-level API implementations.

Analog of the reference's python/ray/_private/worker.py (ray.init/get/put/
wait/kill/cancel/get_actor live here; the module-level ``global_worker``
mirrors the reference's singleton).
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu._private.ids import JobID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.resource_spec import detect_node_resources
from ray_tpu._private.runtime import Runtime

logger = logging.getLogger("ray_tpu")


class Worker:
    def __init__(self):
        self._runtime: Optional[Runtime] = None
        self._lock = threading.Lock()
        self.job_id: Optional[JobID] = None
        self.namespace: str = "default"

    @property
    def connected(self) -> bool:
        return self._runtime is not None

    @property
    def runtime(self) -> Runtime:
        rt = self._runtime
        if rt is not None and getattr(rt, "is_client", False) and rt.closed:
            # The head connection died (head restart): drop the stale
            # client runtime so the next use reconnects.
            with self._lock:
                if self._runtime is rt:
                    self.set_runtime(None)
        if self._runtime is None:
            # Auto-init on first use, matching the reference's behavior of
            # implicit ray.init() in ray.get/put/remote. In a daemon/worker
            # execution context this binds a ClientRuntime wired to the
            # head (never an isolated local runtime — the anti-split-brain
            # rule; reference: every worker embeds a CoreWorker connected
            # to the GCS, core_worker.cc:1762).
            init()
        return self._runtime

    def set_runtime(self, runtime: Optional[Runtime], job_id=None):
        self._runtime = runtime
        self.job_id = job_id


global_worker = Worker()


def _client_context_address():
    """Detect a daemon/worker execution context: returns the head's
    (host, port) when this process should bind a ClientRuntime, else
    None (this process is — or may become — a head/driver)."""
    from ray_tpu._private import multinode as _mn
    daemon = _mn._current_daemon
    if daemon is not None:
        return tuple(daemon.head_address)
    addr = os.environ.get("RAY_TPU_HEAD_ADDRESS")
    if addr:
        host, _, port = addr.rpartition(":")
        return (host or "127.0.0.1", int(port))
    return None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    num_gpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    object_store_memory: Optional[int] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    logging_level: int = logging.INFO,
    include_dashboard: Optional[bool] = None,
    runtime_env: Optional[dict] = None,
    log_to_driver: bool = True,
    _memory: Optional[float] = None,
    _system_config: Optional[dict] = None,
    **kwargs,
) -> "ClientContext":
    """Start (or connect to) a cluster.

    Round 1 runs a single-node in-process cluster; ``address`` other than
    None/"local"/"auto" is reserved for the multi-node control plane.
    """
    with global_worker._lock:
        if global_worker.connected:
            if ignore_reinit_error:
                return ClientContext(global_worker)
            raise RuntimeError(
                "Calling init() again after it has already been called. "
                "Pass ignore_reinit_error=True to suppress this error.")
        client_addr = _client_context_address()
        if client_addr is not None:
            # User code executing inside a node daemon or a worker
            # subprocess: bind a head-connected ClientRuntime so nested
            # .remote(), get_actor, refs, and PGs all resolve cluster-wide
            # (_private/client_runtime.py; reference: CoreWorker-in-every-
            # worker, gcs_actor_manager.cc:241 named-actor resolution).
            from ray_tpu._private.client_runtime import ClientRuntime
            runtime = ClientRuntime(client_addr)
            global_worker.set_runtime(runtime, runtime.job_id)
            global_worker.namespace = namespace or runtime.namespace
            return ClientContext(global_worker)
        if address is not None and address.startswith("ray://"):
            raise ValueError(
                f"Thin-client connections use the client API: "
                f"`api = ray_tpu.util.client.connect({address!r})` against "
                "a driver running `ray_tpu.util.client.serve()`.")
        if address not in (None, "local", "auto"):
            # Design stance (differs from the reference): the DRIVER is
            # the head. Remote machines join as node daemons (`ray-tpu
            # start --address`), and remote DRIVERS attach through the
            # thin client — there is no detached-GCS mode to connect to.
            raise ValueError(
                f"init(address={address!r}): this runtime has no "
                "detached cluster to connect to — the driver IS the "
                "head. To add this machine to a cluster as a worker "
                f"node: `ray-tpu start --address {address}`. To drive "
                "a remote cluster from here: `api = ray_tpu.util."
                f"client.connect({address!r})` against a driver "
                "running `ray_tpu.util.client.serve()`.")
        if num_tpus is None and num_gpus is not None:
            # GPU-option compatibility: the reference's num_gpus maps onto
            # the accelerator resource, which is TPU here.
            num_tpus = num_gpus
        node = detect_node_resources(
            num_cpus=num_cpus, num_tpus=num_tpus, memory=_memory,
            resources=resources)
        job_id = JobID.next()
        runtime = Runtime(node, job_id, system_config=_system_config,
                          log_to_driver=log_to_driver)
        global_worker.set_runtime(runtime, job_id)
        if namespace:
            global_worker.namespace = namespace
        logging.basicConfig(level=logging_level)
        atexit.register(_atexit_shutdown)
        # Head failover: with persisted serve deployments in the
        # gcs_store, replay them in the background now that the worker
        # wiring is attached (deploys run through the normal actor API).
        try:
            runtime.maybe_rehydrate_serve_async()
        except Exception:  # noqa: BLE001 - rehydration is best-effort
            logging.getLogger(__name__).exception(
                "serve rehydration trigger failed")
        return ClientContext(global_worker)


def _atexit_shutdown():
    try:
        shutdown()
    except Exception:  # noqa: BLE001
        pass


def shutdown() -> None:
    with global_worker._lock:
        if global_worker._runtime is not None:
            global_worker._runtime.shutdown()
            global_worker.set_runtime(None)
            global_worker.namespace = "default"


def is_initialized() -> bool:
    return global_worker.connected


def start_head_server(port: int = 0, host: str = "127.0.0.1"):
    """Open this driver's node-registration endpoint so `ray-tpu start
    --address host:port` daemons (other processes/hosts) can join the
    cluster as schedulable nodes (reference: `ray start --head` GCS).
    Returns (host, port).

    SECURITY: the control-plane protocol is unauthenticated cloudpickle —
    any peer that can reach the port gets arbitrary code execution (same
    trust model as the reference's GCS). The default bind is loopback;
    pass host="0.0.0.0" explicitly to serve a real multi-host cluster,
    and only on a trusted network."""
    if not is_initialized():
        init()
    return global_worker.runtime.start_head_server(host, port)


class ClientContext:
    """Return value of ``init`` — address info + context-manager support."""

    def __init__(self, worker: Worker):
        self._worker = worker
        self.address_info = {
            "node_id": worker.runtime.head_node_id.hex(),
            "address": "local",
            "num_cpus": worker.runtime.node_resources.num_cpus,
            "num_tpus": worker.runtime.node_resources.num_tpus,
        }

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        shutdown()

    def __getitem__(self, key):
        return self.address_info[key]


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("Calling put() on an ObjectRef is not allowed.")
    return global_worker.runtime.put(value)


def broadcast(object_ref: ObjectRef, *,
              fanout: Optional[int] = None) -> dict:
    """Eagerly replicate ``object_ref``'s payload onto every live node
    through a bounded-fanout spanning tree (collective dataplane). A
    hint, not a requirement: tasks using the ref afterwards read a
    local replica instead of pulling from one source. Returns a summary
    dict ({"nodes", "depth", "edges", ...}) describing the tree."""
    if not isinstance(object_ref, ObjectRef):
        raise TypeError("broadcast() expects an ObjectRef, got "
                        f"{type(object_ref).__name__}")
    return global_worker.runtime.broadcast(object_ref, fanout=fanout)


def get(object_refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    is_single = isinstance(object_refs, ObjectRef)
    refs = [object_refs] if is_single else list(object_refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(
                f"get() expects ObjectRef or a list of ObjectRefs, got "
                f"{type(r).__name__}")
    values = global_worker.runtime.get(refs, timeout)
    return values[0] if is_single else values


def wait(object_refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    refs = list(object_refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() expects a list of unique ObjectRefs.")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError("wait() expects a list of ObjectRefs.")
    if num_returns <= 0:
        raise ValueError("num_returns must be > 0")
    if num_returns > len(refs):
        raise ValueError(
            f"num_returns ({num_returns}) cannot exceed the number of refs "
            f"({len(refs)})")
    return global_worker.runtime.wait(refs, num_returns, timeout, fetch_local)


def kill(actor, *, no_restart: bool = True) -> None:
    from ray_tpu.actor import ActorHandle
    if not isinstance(actor, ActorHandle):
        raise TypeError("kill() expects an ActorHandle.")
    global_worker.runtime.kill_actor(actor._actor_id, no_restart)


def cancel(object_ref: ObjectRef, *, force: bool = False,
           recursive: bool = True) -> None:
    global_worker.runtime.cancel(object_ref, force)


def get_actor(name: str, namespace: Optional[str] = None):
    from ray_tpu.actor import ActorHandle
    runtime = global_worker.runtime
    actor_id = runtime.get_named_actor(
        name, namespace or global_worker.namespace)
    state = runtime.actor_state(actor_id)
    try:
        cls = runtime.functions.load(state.creation_spec.function_id)
    except KeyError:
        # Class bytes unavailable (unpicklable head-local class looked up
        # from a client runtime): the handle still works — methods bind by
        # name, the class is only cosmetic here.
        cls = None
    return ActorHandle(actor_id, cls, name=name,
                       class_name=getattr(state, "class_name", ""))


def cluster_resources() -> Dict[str, float]:
    return global_worker.runtime.cluster_resources()


def available_resources() -> Dict[str, float]:
    return global_worker.runtime.available_resources()


def nodes() -> List[dict]:
    return global_worker.runtime.scheduler.nodes_snapshot()


def cluster_usage() -> dict:
    """Per-node resource/object-store/memory usage synced from the node
    daemons (the ray-syncer view, _private/syncer.py — reference:
    common/ray_syncer/ray_syncer.h gossip aggregated by the GCS). Keys:
    ``nodes`` (node_id → component payloads), ``available_total``,
    ``version``. Empty until daemons have reported (one health-check
    period); the head node itself schedules in-process and is not
    listed."""
    srv = getattr(global_worker.runtime, "_head_server", None)
    if srv is not None:
        return srv.syncer.digest()
    # In-daemon execution (TPU tasks / actor methods on a node daemon):
    # serve the gossiped digest the head pushes on health pings.
    from ray_tpu._private import multinode as _mn
    daemon = _mn._current_daemon
    if daemon is not None:
        digest = daemon.cluster_digest.get()
        if digest is not None:
            return digest
    return {"version": 0, "nodes": {}, "available_total": {}}


def free(object_refs: Sequence[ObjectRef]) -> None:
    global_worker.runtime.free_objects(
        [r.object_id() for r in object_refs])


def get_tpu_ids() -> List[int]:
    """TPU chip ids assigned to the current task/actor (analog of the
    reference's get_gpu_ids, python/ray/_private/worker.py:832). Concurrent
    tasks receive disjoint chip sets; fractional requests (<1 chip) share
    and get []."""
    from ray_tpu._private.runtime import current_task_spec
    spec = current_task_spec()
    if spec is None:
        return []
    ids = getattr(spec, "_tpu_ids", None)
    if ids is None and spec.actor_id is not None:
        # Actor methods inherit the chips reserved at actor creation.
        state = global_worker.runtime.actor_state(spec.actor_id)
        if state is not None:
            ids = getattr(state.creation_spec, "_tpu_ids", None)
    return sorted(ids or [])
