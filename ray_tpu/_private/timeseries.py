"""Head-side windowed time-series store for cluster metrics.

PR 3's pipeline only ever exposes the *latest* merged sample per series
(one Prometheus scrape of :class:`ClusterMetrics`); the reference punts
history to an external Prometheus. A TPU-native cluster must close the
autoscaling loop and diagnose head saturation with zero external infra,
so :meth:`ClusterMetrics.update` feeds every arriving sample into this
store:

* Per series — keyed ``(metric_name, sorted label items)`` where labels
  are the metric's tag values plus the origin's ``node_id``/``pid``/
  ``component`` — a raw ring at ~1s buckets plus 10s and 60s rollup
  rings, all bounded by the retention window
  (``RAY_TPU_TIMESERIES_WINDOW_S``, default 300s; ``<= 0`` disables the
  store entirely).
* Derivations over any window: counter → rate that is reset-safe across
  process restarts (a value drop counts the new value as the delta,
  never a negative), gauge → last/avg/max, histogram → windowed
  p50/p95 by diffing cumulative bucket counts against the sample at the
  window start.
* Bounded memory: at most ``RAY_TPU_TIMESERIES_MAX_SERIES`` series
  (default 4096; extra series are counted in ``dropped_series``, not
  stored), and staleness eviction wired to membership death pushes —
  ``mark_node_dead`` starts the clock for every series carrying that
  ``node_id`` label, idle series age out after the window passes (safe:
  agents resend full snapshots every ~60s, re-stamping live series).

All internal timestamps are ``time.monotonic()`` — query responses
carry ``now`` so callers can turn point timestamps into ages.
"""

from __future__ import annotations

import bisect
import os
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

DEFAULT_WINDOW_S = 300.0
DEFAULT_MAX_SERIES = 4096
#: Raw ring horizon: the most recent slice keeps ~1s resolution; the
#: 10s/60s rollups carry the rest of the window.
RAW_HORIZON_S = 120.0
ROLLUP_STEPS = (10, 60)


def configured_window_s() -> float:
    """Retention window; honors the documented uppercase env spelling
    first, then the flag table (live runtime config > env > default)."""
    raw = os.environ.get("RAY_TPU_TIMESERIES_WINDOW_S", "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return float(runtime_config_value("timeseries_window_s",
                                      DEFAULT_WINDOW_S))


def configured_max_series() -> int:
    raw = os.environ.get("RAY_TPU_TIMESERIES_MAX_SERIES", "")
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return int(runtime_config_value("timeseries_max_series",
                                    DEFAULT_MAX_SERIES))


class _Series:
    """One labelled stream: raw ring + per-step rollup rings.

    Points are ``[bucket_ts, last, sum, count]`` — cumulative metric
    value plus fold stats so coarse steps keep gauge averages honest.
    Histogram points store ``(bucket_counts_tuple, sum, count)`` as
    ``last`` (cumulative, diffed at query time)."""

    __slots__ = ("name", "kind", "labels", "boundaries",
                 "raw", "rollups", "last_seen", "dead_at", "born")

    def __init__(self, name: str, kind: str, labels: Dict[str, str],
                 boundaries: Tuple[float, ...], window_s: float):
        self.name = name
        self.kind = kind
        self.labels = labels
        self.boundaries = boundaries
        raw_len = int(min(window_s, RAW_HORIZON_S)) + 2
        self.raw: deque = deque(maxlen=max(raw_len, 4))
        self.rollups: Dict[int, deque] = {
            step: deque(maxlen=int(window_s // step) + 2)
            for step in ROLLUP_STEPS}
        self.last_seen = time.monotonic()
        self.dead_at: Optional[float] = None
        self.born: Optional[float] = None  # first sample's timestamp

    def append(self, now: float, value: Any) -> None:
        self.last_seen = now
        if self.born is None:
            self.born = now
        self._fold(self.raw, now - now % 1.0, value)
        for step, ring in self.rollups.items():
            self._fold(ring, now - now % step, value)

    @staticmethod
    def _fold(ring: deque, bucket_ts: float, value: Any) -> None:
        if ring and ring[-1][0] == bucket_ts:
            point = ring[-1]
            point[1] = value
            if isinstance(value, (int, float)):
                point[2] += value
            point[3] += 1
        else:
            total = value if isinstance(value, (int, float)) else 0.0
            ring.append([bucket_ts, value, total, 1])

    def _ring_for(self, window: float,
                  step: Optional[float]) -> Tuple[deque, float]:
        """Pick the finest ring whose horizon covers ``window`` (or the
        one matching an explicit ``step``)."""
        if step is not None:
            if step < ROLLUP_STEPS[0]:
                return self.raw, 1.0
            chosen = ROLLUP_STEPS[0]
            for s in ROLLUP_STEPS:
                if step >= s:
                    chosen = s
            return self.rollups[chosen], float(chosen)
        if window <= RAW_HORIZON_S:
            return self.raw, 1.0
        return self.rollups[ROLLUP_STEPS[0]], float(ROLLUP_STEPS[0])

    def window_points(self, now: float, window: float,
                      step: Optional[float] = None) -> List[list]:
        """Points inside ``[now - window, now]`` plus one baseline point
        just before the window start (rate/diff anchors)."""
        ring, _res = self._ring_for(window, step)
        start = now - window
        pts = list(ring)
        idx = bisect.bisect_left([p[0] for p in pts], start)
        baseline = max(0, idx - 1)
        return pts[baseline:]

    # -- derivations ---------------------------------------------------

    def rate(self, now: float, window: float) -> float:
        """Reset-safe counter rate: sum of positive deltas (a drop means
        the process restarted — the new cumulative value IS the delta)
        over the observed span. A series BORN inside the window gets an
        implicit 0 baseline: a counter cell exists only after its first
        inc, so its first cumulative sample is in-window activity (the
        first node death must rate > 0, not anchor the baseline)."""
        pts = self.window_points(now, window)
        if not pts:
            return 0.0
        total = 0.0
        if self.born is not None and pts[0][0] <= self.born:
            total += pts[0][1]
        for prev, cur in zip(pts, pts[1:]):
            delta = cur[1] - prev[1]
            total += delta if delta >= 0 else cur[1]
        span = pts[-1][0] - pts[0][0]
        if span <= 0:
            # Lone birth bucket: spread the credit over the elapsed
            # window so the first evaluation already sees the spike.
            span = max(1.0, min(window, now - pts[0][0]))
            return total / span if total > 0 else 0.0
        return total / span

    def gauge_summary(self, now: float, window: float) -> Dict[str, float]:
        pts = self.window_points(now, window)
        if not pts:
            return {"last": 0.0, "avg": 0.0, "max": 0.0}
        total = sum(p[2] for p in pts)
        count = sum(p[3] for p in pts)
        return {"last": float(pts[-1][1]),
                "avg": total / count if count else 0.0,
                "max": max(float(p[1]) for p in pts)}

    def histogram_delta(self, now: float, window: float
                        ) -> Tuple[List[float], float, int]:
        """Windowed (bucket_deltas, sum_delta, count_delta): current
        cumulative state minus the baseline at the window start, clamped
        at zero per bucket so restarts never go negative."""
        pts = self.window_points(now, window)
        if len(pts) < 2:
            # A lone sample carries cumulative state from before the
            # window — without a baseline there is no derivable delta
            # (same rule counter rates follow).
            return [], 0.0, 0
        cur_b, cur_s, cur_c = pts[-1][1]
        base_b, base_s, base_c = pts[0][1]
        if len(base_b) != len(cur_b):
            base_b = (0.0,) * len(cur_b)
        deltas = [max(0.0, c - b) for c, b in zip(cur_b, base_b)]
        return deltas, max(0.0, cur_s - base_s), max(0, cur_c - base_c)

    def percentile(self, now: float, window: float, q: float) -> float:
        deltas, _s, _c = self.histogram_delta(now, window)
        return _bucket_percentile(self.boundaries, deltas, q)


def _bucket_percentile(boundaries: Tuple[float, ...],
                       buckets: Iterable[float], q: float) -> float:
    """The util/metrics.py bucket-walk: smallest boundary whose
    cumulative count reaches q% of the total."""
    buckets = list(buckets)
    total = sum(buckets)
    if total <= 0 or not boundaries:
        return 0.0
    target = (q / 100.0) * total
    cum = 0.0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= target:
            return boundaries[min(i, len(boundaries) - 1)]
    return boundaries[-1]


class TimeSeriesStore:
    """Bounded windowed store every scale-era signal reads from."""

    def __init__(self, window_s: Optional[float] = None,
                 max_series: Optional[int] = None,
                 staleness: float = 30.0):
        self.window_s = (configured_window_s() if window_s is None
                         else float(window_s))
        self.max_series = (configured_max_series() if max_series is None
                           else int(max_series))
        self.staleness = staleness
        self.enabled = self.window_s > 0
        self.dropped_series = 0
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                           _Series] = {}

    # -- ingest --------------------------------------------------------

    def ingest_batch(self, node_id: str, pid: int, component: str,
                     entries: Iterable[Dict[str, Any]],
                     now: Optional[float] = None) -> None:
        """Feed one metrics_batch's entries (snapshot or diff — values
        are cumulative either way)."""
        if not self.enabled:
            return
        if now is None:
            now = time.monotonic()
        origin = {"node_id": node_id or "", "pid": str(pid),
                  "component": component or ""}
        with self._lock:
            for entry in entries:
                name = entry.get("name")
                kind = entry.get("type")
                if not name or not kind:
                    continue
                tag_keys = tuple(entry.get("tag_keys") or ())
                boundaries = tuple(entry.get("boundaries") or ())
                if kind == "histogram":
                    sums = entry.get("sums", {})
                    counts = entry.get("counts", {})
                    for skey, bucket_counts in (
                            entry.get("buckets") or {}).items():
                        value = (tuple(float(c) for c in bucket_counts),
                                 float(sums.get(skey, 0.0)),
                                 int(counts.get(skey, 0)))
                        self._append(name, kind, tag_keys, skey, origin,
                                     boundaries, now, value)
                else:
                    for skey, value in (entry.get("series") or {}).items():
                        self._append(name, kind, tag_keys, skey, origin,
                                     boundaries, now, float(value))

    def _append(self, name: str, kind: str, tag_keys: Tuple[str, ...],
                series_key: Any, origin: Dict[str, str],
                boundaries: Tuple[float, ...], now: float,
                value: Any) -> None:
        labels = dict(origin)
        if isinstance(series_key, (tuple, list)):
            labels.update(zip(tag_keys, (str(v) for v in series_key)))
        key = (name, tuple(sorted(labels.items())))
        series = self._series.get(key)
        if series is None:
            if len(self._series) >= self.max_series:
                self.dropped_series += 1
                return
            series = self._series[key] = _Series(
                name, kind, labels, boundaries, self.window_s)
        elif series.kind != kind:
            series = self._series[key] = _Series(
                name, kind, labels, boundaries, self.window_s)
        series.dead_at = None
        series.append(now, value)

    # -- eviction ------------------------------------------------------

    def mark_node_dead(self, node_id: str) -> None:
        now = time.monotonic()
        with self._lock:
            for series in self._series.values():
                if (series.labels.get("node_id") == node_id
                        and series.dead_at is None):
                    series.dead_at = now

    def evict_stale(self) -> None:
        if not self.enabled:
            return
        now = time.monotonic()
        idle_horizon = max(self.window_s, RAW_HORIZON_S)
        with self._lock:
            doomed = [key for key, s in self._series.items()
                      if (s.dead_at is not None
                          and now - s.dead_at > self.staleness)
                      or now - s.last_seen > idle_horizon]
            for key in doomed:
                del self._series[key]

    # -- queries -------------------------------------------------------

    def series_count(self) -> int:
        with self._lock:
            return len(self._series)

    def names(self) -> List[str]:
        with self._lock:
            return sorted({name for name, _labels in self._series})

    def _select(self, name: str,
                labels: Optional[Dict[str, str]]) -> List[_Series]:
        with self._lock:
            out = []
            for (sname, _lkey), series in self._series.items():
                if sname != name:
                    continue
                if labels and any(series.labels.get(k) != str(v)
                                  for k, v in labels.items()):
                    continue
                out.append(series)
            return out

    def query(self, name: str, labels: Optional[Dict[str, str]] = None,
              window: Optional[float] = None,
              step: Optional[float] = None) -> Dict[str, Any]:
        """Raw points + a per-series summary for every matching series.
        Timestamps are monotonic; ``now`` anchors them."""
        now = time.monotonic()
        w = self.window_s if window is None else min(float(window),
                                                    self.window_s)
        result: List[Dict[str, Any]] = []
        for series in self._select(name, labels):
            pts = series.window_points(now, w, step)
            row: Dict[str, Any] = {
                "labels": dict(series.labels),
                "kind": series.kind,
            }
            if series.kind == "histogram":
                deltas, sum_d, count_d = series.histogram_delta(now, w)
                row["points"] = [[p[0], p[1][2]] for p in pts]  # counts
                row["summary"] = {
                    "count": count_d, "sum": sum_d,
                    "rate": count_d / w if w > 0 else 0.0,
                    "p50": _bucket_percentile(series.boundaries, deltas, 50),
                    "p95": _bucket_percentile(series.boundaries, deltas, 95),
                }
            else:
                row["points"] = [[p[0], p[1]] for p in pts]
                if series.kind == "counter":
                    row["summary"] = {"rate": series.rate(now, w),
                                      "last": float(pts[-1][1])
                                      if pts else 0.0}
                else:
                    row["summary"] = series.gauge_summary(now, w)
            result.append(row)
        return {"name": name, "window_s": w, "now": now, "series": result}

    def counter_rate(self, name: str,
                     labels: Optional[Dict[str, str]] = None,
                     window: Optional[float] = None,
                     group_by: Optional[str] = None) -> Dict[str, float]:
        """Summed windowed rates, grouped by one label (or "" for all)."""
        now = time.monotonic()
        w = self.window_s if window is None else min(float(window),
                                                    self.window_s)
        out: Dict[str, float] = {}
        for series in self._select(name, labels):
            key = series.labels.get(group_by, "") if group_by else ""
            out[key] = out.get(key, 0.0) + series.rate(now, w)
        return out

    def gauge_stats(self, name: str,
                    labels: Optional[Dict[str, str]] = None,
                    window: Optional[float] = None,
                    group_by: Optional[str] = None
                    ) -> Dict[str, Dict[str, float]]:
        """Per-group {last_sum, last_max, avg_sum, avg_max} — sum for
        additive gauges (queue depth, bytes), max for replicated views
        (replica count seen by several routers)."""
        now = time.monotonic()
        w = self.window_s if window is None else min(float(window),
                                                    self.window_s)
        out: Dict[str, Dict[str, float]] = {}
        for series in self._select(name, labels):
            key = series.labels.get(group_by, "") if group_by else ""
            summ = series.gauge_summary(now, w)
            g = out.setdefault(key, {"last_sum": 0.0, "last_max": 0.0,
                                     "avg_sum": 0.0, "avg_max": 0.0})
            g["last_sum"] += summ["last"]
            g["last_max"] = max(g["last_max"], summ["last"])
            g["avg_sum"] += summ["avg"]
            g["avg_max"] = max(g["avg_max"], summ["avg"])
        return out

    def histogram_stats(self, name: str,
                        labels: Optional[Dict[str, str]] = None,
                        window: Optional[float] = None,
                        group_by: Optional[str] = None
                        ) -> Dict[str, Dict[str, float]]:
        """Per-group windowed {count, sum, mean, rate, p50, p95} with
        bucket deltas merged across series before the percentile walk."""
        now = time.monotonic()
        w = self.window_s if window is None else min(float(window),
                                                    self.window_s)
        merged: Dict[str, Dict[str, Any]] = {}
        for series in self._select(name, labels):
            key = series.labels.get(group_by, "") if group_by else ""
            deltas, sum_d, count_d = series.histogram_delta(now, w)
            m = merged.setdefault(key, {"buckets": [], "sum": 0.0,
                                        "count": 0,
                                        "boundaries": series.boundaries})
            if len(m["buckets"]) < len(deltas):
                m["buckets"] += [0.0] * (len(deltas) - len(m["buckets"]))
            for i, d in enumerate(deltas):
                m["buckets"][i] += d
            m["sum"] += sum_d
            m["count"] += count_d
        out: Dict[str, Dict[str, float]] = {}
        for key, m in merged.items():
            count = m["count"]
            out[key] = {
                "count": count, "sum": m["sum"],
                "mean": m["sum"] / count if count else 0.0,
                "rate": count / w if w > 0 else 0.0,
                "p50": _bucket_percentile(m["boundaries"], m["buckets"], 50),
                "p95": _bucket_percentile(m["boundaries"], m["buckets"], 95),
            }
        return out
