"""Single-node object store.

The analog of the reference's in-process memory store + plasma store
(reference: src/ray/core_worker/store_provider/memory_store/memory_store.h,
src/ray/object_manager/plasma/store.h). Objects are immutable once sealed;
``get`` blocks until the object is sealed or the store is told the object
failed (in which case the stored error is raised at the caller).

Two payload kinds are supported:

* **Inline values** — Python objects stored by reference (thread-backend fast
  path; the zero-copy analog of plasma buffers shared within one address
  space). Mutation of gotten objects is undefined behavior, as with plasma.
* **Serialized values** — bytes produced by the serializer; deserialized on
  first get and cached.

Reference counting: the driver owns all objects in round 1 (single-node);
``free`` evicts explicitly. Distributed ownership arrives with the multi-node
store.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu._private import builtin_metrics
from ray_tpu._private.channel import Backoff
from ray_tpu._private.ids import ObjectID
from ray_tpu.exceptions import GetTimeoutError, ObjectFreedError, ObjectLostError

logger = logging.getLogger(__name__)

# _restore's tier-miss sentinel: the spilled payload is gone (missing /
# truncated / injected restore error). Distinct from None, which means
# a concurrent free() won.
_RESTORE_MISS = object()


def _estimate_size(value: Any) -> int:
    """Cheap size estimate for inline values — exact for the payloads that
    matter to spilling (arrays, bytes); containers of arrays count their
    array contents one level deep."""
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(value, (bytes, bytearray)):
        return len(value)
    if isinstance(value, str):
        return len(value)
    if isinstance(value, (list, tuple)) and value:
        return sum(_estimate_size(v) for v in value)
    if isinstance(value, dict) and value:
        return sum(_estimate_size(v) for v in value.values())
    return 64


@dataclass
class _Entry:
    event: threading.Event = field(default_factory=threading.Event)
    value: Any = None
    serialized: Optional[bytes] = None
    deserialized: bool = False
    is_exception: bool = False
    freed: bool = False
    in_native: bool = False
    size_bytes: int = 0
    create_time: float = 0.0
    spilled_path: Optional[str] = None  # spill URI (see _private/spill.py)
    spilled_len: int = 0  # on-disk payload length (truncation check)
    pinned: bool = False  # unpicklable values are never spill victims
    # Sealed-but-elsewhere (node-daemon resident, multinode data plane):
    # get() materializes through this callable exactly once. The daemon
    # keeps the primary copy until the ref drops (plasma semantics: a get
    # copies locally, the primary stays pinned on the producing node).
    remote_fetch: Optional[Callable[[], Any]] = None
    fetching: bool = False  # one pull at a time; other getters wait
    # Completion callbacks (reference: memory_store GetAsync): fired once,
    # outside the store lock, when the entry seals. None until someone
    # subscribes, so entries that nobody watches pay one attribute read.
    seal_callbacks: Optional[list] = None


class ObjectStore:
    # Arrays at or above this size go to the native shm store (plasma
    # analog); below it, inline references win (same address space).
    NATIVE_THRESHOLD = 1 << 20

    def __init__(self, deserializer: Optional[Callable[[bytes], Any]] = None,
                 native_capacity: int = 0, use_native: bool = True,
                 spill_threshold_bytes: int = 0,
                 spill_directory: Optional[str] = None,
                 spill_backend=None):
        self._entries: Dict[ObjectID, _Entry] = {}
        self._lock = threading.Lock()
        self._deserializer = deserializer
        self._total_bytes = 0
        # Spilling (reference: raylet LocalObjectManager spill/restore +
        # plasma fallback allocation): past the threshold, the coldest
        # sealed values are cloudpickled through the spill backend and
        # restored on get. ``spill_backend`` (a _private.spill.SpillBackend)
        # wins over the legacy ``spill_directory`` (file:// over that dir).
        self._spill_threshold = spill_threshold_bytes
        self._spill_dir = spill_directory
        self._spill_backend = spill_backend
        # Invoked (outside get()'s lock) when a restore tier-misses:
        # returns True if recovery (invalidate + lineage reconstruction)
        # was initiated — the getter loops back and waits for the
        # re-seal. Installed by the runtime.
        self.restore_miss_hook: Optional[Callable[[ObjectID], bool]] = None
        self._spilled_bytes = 0
        self._spill_count = 0
        self._restore_count = 0
        # Insertion-ordered spill candidates (puts are time-ordered, so the
        # front is the coldest) — avoids O(n) victim scans under the lock.
        self._spill_order: Dict[ObjectID, None] = {}
        self._native = None
        if use_native and native_capacity > 0 and os.environ.get(
                "RAY_TPU_NATIVE_STORE", "1") != "0":
            try:
                from ray_tpu._private.native_store import NativeObjectStore
                self._native = NativeObjectStore(capacity=native_capacity)
            except Exception:  # noqa: BLE001 - no compiler: pure-Python path
                self._native = None

    @property
    def native(self):
        return self._native

    def _try_put_native(self, object_id: ObjectID, value: Any) -> bool:
        """Large contiguous numpy arrays go to the shm arena; gets return
        zero-copy read-only views (reference: plasma put/get of tensors)."""
        import numpy as np
        if self._native is None or not isinstance(value, np.ndarray):
            return False
        if value.nbytes < self.NATIVE_THRESHOLD or value.dtype == object:
            return False
        return self._native.put_array(object_id.hex(), value)

    def set_deserializer(self, fn: Callable[[bytes], Any]) -> None:
        self._deserializer = fn

    def _entry(self, object_id: ObjectID) -> _Entry:
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None:
                entry = _Entry()
                self._entries[object_id] = entry
            return entry

    # -- seal subscriptions ----------------------------------------------

    def on_sealed(self, object_id: ObjectID,
                  callback: Callable[[ObjectID], None]) -> None:
        """Invoke ``callback(object_id)`` once the entry seals (value,
        error, free, or shutdown fail-all). Fires immediately if already
        sealed; otherwise from whichever thread seals the entry, outside
        the store lock. The event-driven analog of the reference memory
        store's GetAsync — waiters (e.g. the serve router's completion
        tracking) subscribe instead of polling ``wait``."""
        entry = self._entry(object_id)
        with self._lock:
            if not entry.event.is_set():
                if entry.seal_callbacks is None:
                    entry.seal_callbacks = []
                entry.seal_callbacks.append(callback)
                return
        self._fire_seal_callbacks([callback], object_id)

    @staticmethod
    def _take_seal_callbacks(entry: _Entry) -> Optional[list]:
        """Detach the callback list (call with the store lock held)."""
        cbs = entry.seal_callbacks
        entry.seal_callbacks = None
        return cbs

    @staticmethod
    def _fire_seal_callbacks(cbs: Optional[list], object_id: ObjectID) -> None:
        if not cbs:
            return
        for cb in cbs:
            try:
                cb(object_id)
            except Exception:  # noqa: BLE001 - subscriber bug must not
                logger.exception(      # poison the sealing thread
                    "seal callback for %s raised", object_id.hex())

    # -- write side -------------------------------------------------------

    def put_inline(self, object_id: ObjectID, value: Any,
                   is_exception: bool = False) -> None:
        entry = self._entry(object_id)
        in_native = (not is_exception
                     and self._try_put_native(object_id, value))
        with self._lock:
            # Objects are immutable once sealed (plasma semantics): first
            # write wins, racing writers (e.g. a completing task vs. a kill
            # sealing errors) are dropped.
            if entry.event.is_set():
                return
            if in_native:
                entry.in_native = True
                entry.size_bytes = value.nbytes
            else:
                entry.value = value
                entry.size_bytes = _estimate_size(value)
                self._total_bytes += entry.size_bytes
                if (self._spill_threshold and not is_exception
                        and entry.size_bytes > 0):
                    self._spill_order[object_id] = None
            entry.deserialized = True
            entry.is_exception = is_exception
            entry.create_time = time.time()
            entry.event.set()
            cbs = self._take_seal_callbacks(entry)
        self._fire_seal_callbacks(cbs, object_id)
        self._maybe_spill()

    def put_remote(self, object_id: ObjectID, fetch_fn: Callable[[], Any],
                   size_bytes: int = 0) -> None:
        """Seal an object whose value lives on another node (daemon-
        resident large result): ready for contains/wait immediately,
        materialized through ``fetch_fn`` on first get (the pull half of
        the reference's ObjectManager data plane)."""
        entry = self._entry(object_id)
        with self._lock:
            if entry.event.is_set():
                return
            entry.remote_fetch = fetch_fn
            entry.size_bytes = size_bytes
            entry.create_time = time.time()
            entry.event.set()
            cbs = self._take_seal_callbacks(entry)
        self._fire_seal_callbacks(cbs, object_id)

    def replace_remote_fetch(self, object_id: ObjectID,
                             fetch_fn: Callable[[], Any],
                             size_bytes: int = 0) -> bool:
        """Re-point a sealed-but-remote entry at another holder's fetch
        (the replica recovery tier: the original holder died but a
        byte-identical copy survives on a peer). No-op — returns False —
        if the value already materialized locally or the entry is gone."""
        with self._lock:
            entry = self._entries.get(object_id)
            if entry is None or entry.freed or not entry.event.is_set() \
                    or entry.remote_fetch is None:
                return False
            entry.remote_fetch = fetch_fn
            if size_bytes:
                entry.size_bytes = size_bytes
            return True

    def size_of(self, object_id: ObjectID) -> int:
        """Known payload size in bytes (0 when unknown/absent). Remote
        stub entries carry the daemon-reported size, so the head can
        score argument-byte locality without materializing anything."""
        with self._lock:
            entry = self._entries.get(object_id)
            return 0 if entry is None or entry.freed else entry.size_bytes

    def is_materialized(self, object_id: ObjectID) -> bool:
        """True when the value is locally available (not a pending remote
        fetch) — node death cannot lose a materialized object."""
        with self._lock:
            entry = self._entries.get(object_id)
            return (entry is not None and entry.event.is_set()
                    and not entry.freed and entry.remote_fetch is None)

    def put_serialized(self, object_id: ObjectID, payload: bytes,
                       is_exception: bool = False) -> None:
        entry = self._entry(object_id)
        with self._lock:
            if entry.event.is_set():
                return
            entry.serialized = payload
            entry.is_exception = is_exception
            entry.size_bytes = len(payload)
            entry.create_time = time.time()
            self._total_bytes += len(payload)
            entry.event.set()
            cbs = self._take_seal_callbacks(entry)
        self._fire_seal_callbacks(cbs, object_id)
        self._maybe_spill()

    # -- spilling ---------------------------------------------------------

    def _backend(self):
        """The spill backend, built lazily (file:// over the legacy
        directory when no explicit backend was injected)."""
        if self._spill_backend is None:
            from ray_tpu._private.spill import FileSpillBackend
            self._spill_backend = FileSpillBackend(self._spill_dir)
        return self._spill_backend

    def _maybe_spill(self) -> None:
        """Spill coldest sealed values through the backend while over the
        threshold (reference: raylet/local_object_manager.h SpillObjects).
        Victims are serialized outside the lock; a racing free/invalidate
        wins. A victim whose earlier spill file is still valid is dropped
        by reference — no re-serialize, no re-write (the restored-object
        re-spill path)."""
        if not self._spill_threshold or (
                self._spill_dir is None and self._spill_backend is None):
            return
        import cloudpickle

        from ray_tpu._private.spill import SpillFailure
        while True:
            with self._lock:
                if self._total_bytes <= self._spill_threshold:
                    return
                victim = None
                victim_id = None
                # Pop from the insertion-ordered candidates: the front is
                # the coldest; permanently ineligible entries fall out.
                for oid in list(self._spill_order):
                    entry = self._entries.get(oid)
                    if entry is None or entry.freed or entry.pinned \
                            or entry.value is None \
                            or entry.serialized is not None:
                        # serialized retained → spilling frees no memory
                        del self._spill_order[oid]
                        continue
                    if not entry.event.is_set():
                        continue
                    victim, victim_id = entry, oid
                    del self._spill_order[oid]
                    break
                if victim is None:
                    return
                if victim.spilled_path is not None:
                    # Restored-and-since-idle: the on-disk payload is
                    # still valid, so drop the memory copy by reference.
                    victim.value = None
                    self._total_bytes -= victim.size_bytes
                    self._spilled_bytes += victim.size_bytes
                    self._spill_count += 1
                    continue
                value = victim.value
            try:
                payload = cloudpickle.dumps(value)
            except Exception:  # noqa: BLE001 - unpicklable: pin in memory
                with self._lock:
                    victim.pinned = True
                continue
            try:
                uri = self._backend().write(
                    f"spilled-{victim_id.hex()}.bin", payload)
            except SpillFailure:
                # Degrade gracefully: the value stays in memory (the
                # backend already counted the failure); the victim left
                # _spill_order so we don't hot-loop on a broken disk.
                continue
            with self._lock:
                if victim.freed or not victim.event.is_set():
                    pass  # racing free/invalidate won; drop the file
                else:
                    victim.spilled_path = uri
                    victim.spilled_len = len(payload)
                    victim.value = None
                    self._total_bytes -= victim.size_bytes
                    self._spilled_bytes += victim.size_bytes
                    self._spill_count += 1
                    spilled_now = victim.size_bytes
                    uri = None
            if uri is not None:
                self._backend().delete(uri)
                continue
            builtin_metrics.object_spilled_bytes().inc(spilled_now)

    def _restore(self, entry: _Entry, object_id: ObjectID) -> Any:
        """Load a spilled value back (reference: spilled-object restore).

        Returns ``None`` when a concurrent ``free()`` won, and the
        :data:`_RESTORE_MISS` sentinel on a tier miss (file missing /
        truncated / injected restore fault) — the caller falls down the
        recovery hierarchy instead of seeing an exception.

        The spill file stays valid after a successful restore (no
        unlink, ``spilled_path`` kept), so renewed memory pressure can
        drop the copy again by reference — the restored-object pinning
        leak fix."""
        import cloudpickle
        payload = self._backend().read(entry.spilled_path,
                                       entry.spilled_len)
        if payload is not None:
            try:
                value = cloudpickle.loads(payload)
            except Exception:  # noqa: BLE001 - torn/corrupt payload
                payload = None
        if payload is None:
            logger.warning(
                "spilled payload for %s (%s) is unreadable; treating as "
                "a tier miss", object_id.hex(), entry.spilled_path)
            with self._lock:
                if entry.freed:
                    return None
                if entry.spilled_path is not None:
                    if entry.value is None:
                        self._spilled_bytes -= entry.size_bytes
                    entry.spilled_path = None
                return entry.value if entry.value is not None \
                    else _RESTORE_MISS
        with self._lock:
            if entry.freed:
                # A concurrent free() won: don't resurrect or touch the
                # accounting (free already settled it).
                return None
            if entry.value is None and entry.spilled_path is not None:
                entry.value = value
                self._total_bytes += entry.size_bytes
                self._spilled_bytes -= entry.size_bytes
                self._restore_count += 1
                # Re-eligible for (by-reference) re-spill once pressure
                # returns and no reader is mid-get.
                if self._spill_threshold and entry.size_bytes > 0:
                    self._spill_order[object_id] = None
            return entry.value

    def spill_stats(self) -> dict:
        with self._lock:
            return {
                "spilled_bytes_current": self._spilled_bytes,
                "spill_count": self._spill_count,
                "restore_count": self._restore_count,
            }

    # -- read side --------------------------------------------------------

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            entry = self._entries.get(object_id)
        return entry is not None and entry.event.is_set() and not entry.freed

    def wait_ready(self, object_id: ObjectID, timeout: Optional[float]) -> bool:
        entry = self._entry(object_id)
        return entry.event.wait(timeout)

    def get(self, object_id: ObjectID, timeout: Optional[float] = None) -> Any:
        """Return the stored value (deserializing if needed).

        Raises the stored exception if the object holds an error; raises
        GetTimeoutError on timeout. The caller is responsible for re-raising
        TaskError causes appropriately.
        """
        entry = self._entry(object_id)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        busy_backoff = Backoff(initial=0.002, cap=0.05)
        while True:
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            if not entry.event.wait(remaining):
                raise GetTimeoutError(
                    f"Get timed out waiting for object {object_id.hex()} "
                    f"after {timeout}s.")
            with self._lock:
                # Re-check under the lock: a concurrent invalidate() (node
                # death → reconstruction) may have un-sealed the entry
                # between the wait and here; loop back and wait for the
                # reconstructed value instead of reading reset fields.
                if not entry.event.is_set():
                    continue
                fetch = entry.remote_fetch
                if fetch is not None:
                    if entry.fetching:
                        fetch = "busy"  # another getter is pulling
                    else:
                        entry.fetching = True
            if fetch == "busy":
                # One transfer at a time: wait for the in-flight pull to
                # memoize (or fail/invalidate), then re-evaluate —
                # honoring this getter's own deadline.
                if deadline is not None and time.monotonic() > deadline:
                    raise GetTimeoutError(
                        f"Get timed out waiting for remote object "
                        f"{object_id.hex()} after {timeout}s.")
                busy_backoff.sleep()
                continue
            if fetch is None:
                break
            remaining = (None if deadline is None
                         else max(0.0, deadline - time.monotonic()))
            try:
                # Network pull, outside any lock; bounded by the caller's
                # deadline (fetch_fn contract: optional timeout kwarg).
                value = fetch(timeout=remaining)
            except TimeoutError:
                with self._lock:
                    entry.fetching = False
                raise GetTimeoutError(
                    f"Get timed out pulling remote object "
                    f"{object_id.hex()} after {timeout}s.")
            except BaseException as fetch_exc:
                with self._lock:
                    entry.fetching = False
                    # Node death may have raced us: if the entry was
                    # invalidated (reconstruction pending) or re-sealed,
                    # wait for the new value instead of failing the get.
                    raced = (entry.remote_fetch is not fetch
                             or not entry.event.is_set())
                if not raced and isinstance(fetch_exc, ObjectLostError):
                    # The holder died mid-fetch but recovery hasn't
                    # settled this entry yet. remove_node ALWAYS settles
                    # it — re-points the fetch at a replica, restores
                    # from a spill URI, invalidates for a lineage retry,
                    # or seals the loss — so wait briefly for the
                    # verdict instead of racing it to the caller.
                    grace = time.monotonic() + 10.0
                    if deadline is not None:
                        grace = min(grace, deadline)
                    settle_backoff = Backoff(initial=0.002, cap=0.05)
                    while not raced and time.monotonic() < grace:
                        settle_backoff.sleep()
                        with self._lock:
                            raced = (entry.remote_fetch is not fetch
                                     or not entry.event.is_set())
                if raced:
                    continue
                raise
            with self._lock:
                entry.fetching = False
                if entry.remote_fetch is fetch and not entry.freed:
                    entry.value = value
                    entry.deserialized = True
                    entry.remote_fetch = None
                    entry.size_bytes = _estimate_size(value)
                    self._total_bytes += entry.size_bytes
                    if self._spill_threshold and entry.size_bytes > 0:
                        self._spill_order[object_id] = None
                    raced = False
                else:
                    # Invalidate/re-seal won the race: discard this pull
                    # and wait for the authoritative value (freed entries
                    # fall through to the freed check below).
                    raced = not entry.freed
            if raced:
                continue
            self._maybe_spill()
            break
        if entry.freed:
            raise ObjectFreedError(
                f"Object {object_id.hex()} was freed and is no longer available.")
        if entry.in_native:
            # First get pins the object (one store-held reference) and
            # caches the zero-copy view; eviction can't touch it until
            # free(). Reference: plasma client Get holds a buffer ref.
            if entry.value is None:
                arr = self._native.get_array(object_id.hex()) \
                    if self._native is not None else None
                if arr is None:
                    raise ObjectLostError(
                        f"Object {object_id.hex()} was evicted from the "
                        "shared-memory store.")
                entry.value = arr
            return entry.value
        # Snapshot under the lock: a concurrent _maybe_spill may null
        # entry.value at any moment; holding our own reference is safe.
        with self._lock:
            value = entry.value
            needs_restore = (entry.spilled_path is not None
                             and value is None)
        if needs_restore:
            builtin_metrics.record_store_miss()
        else:
            builtin_metrics.record_store_hit()
        if needs_restore:
            value = self._restore(entry, object_id)
            if value is None:
                raise ObjectFreedError(
                    f"Object {object_id.hex()} was freed and is no "
                    "longer available.")
            if value is _RESTORE_MISS:
                # Tier miss: the spill copy is gone. Hand the loss to
                # the runtime's recovery hook (invalidate + lineage
                # re-execution) and re-enter the get to wait for the
                # re-seal; without a hook the loss is terminal.
                hook = self.restore_miss_hook
                recovering = False
                if hook is not None:
                    try:
                        recovering = bool(hook(object_id))
                    except Exception:  # noqa: BLE001 - hook bug ≠ hang
                        logger.exception("restore-miss hook raised")
                if recovering:
                    remaining = (None if deadline is None
                                 else max(0.0,
                                          deadline - time.monotonic()))
                    return self.get(object_id, remaining)
                raise ObjectLostError(
                    f"Object {object_id.hex()} was spilled but its "
                    "payload is no longer readable and no lineage "
                    "recovery is available.")
        if not entry.deserialized:
            if self._deserializer is None:
                raise ObjectLostError(object_id.hex())
            value = self._deserializer(entry.serialized)
            with self._lock:
                entry.value = value
                entry.deserialized = True
        if entry.is_exception:
            # Raise a shallow copy: `raise` attaches the caller's traceback
            # to the exception object, and the traceback's frames hold the
            # very ObjectRef being fetched — raising the stored instance
            # would make the object pin itself (a refcount leak cycle).
            import copy
            exc = copy.copy(value)
            exc.__traceback__ = None
            raise exc
        return value

    def native_array_key(self, object_id: ObjectID) -> Optional[str]:
        """The shm-arena key when this object is an arena-resident array
        (for handing to worker processes as a zero-copy marker)."""
        with self._lock:
            e = self._entries.get(object_id)
            if e is not None and e.event.is_set() and e.in_native \
                    and not e.freed:
                return object_id.hex()
        return None

    def get_if_exception(self, object_id: ObjectID) -> Optional[BaseException]:
        entry = self._entry(object_id)
        if not entry.event.is_set() or not entry.is_exception:
            return None
        if not entry.deserialized and self._deserializer is not None:
            entry.value = self._deserializer(entry.serialized)
            entry.deserialized = True
        return entry.value

    # -- lifecycle --------------------------------------------------------

    def free(self, object_ids) -> None:
        fired = []  # (callbacks, oid) — entries freed before ever sealing
        doomed_uris = []  # spill files deleted outside the lock
        with self._lock:
            for oid in object_ids:
                entry = self._entries.get(oid)
                if entry is not None:
                    if entry.freed:
                        continue  # idempotent: never double-settle accounting
                    entry.freed = True
                    cbs = self._take_seal_callbacks(entry)
                    if cbs:
                        fired.append((cbs, oid))
                    if entry.in_native and self._native is not None:
                        if entry.value is not None:
                            self._native.release(oid.hex())
                        self._native.delete(oid.hex())
                    if entry.spilled_path is not None:
                        doomed_uris.append(entry.spilled_path)
                        if entry.value is None:
                            self._spilled_bytes -= entry.size_bytes
                        entry.spilled_path = None
                    if not entry.in_native and (
                            entry.value is not None
                            or entry.serialized is not None):
                        self._total_bytes -= entry.size_bytes
                    entry.value = None
                    entry.serialized = None
                    entry.remote_fetch = None
                    entry.event.set()
        for uri in doomed_uris:
            self._backend().delete(uri)
        for cbs, oid in fired:
            self._fire_seal_callbacks(cbs, oid)

    def invalidate(self, object_ids) -> None:
        """Un-seal objects whose primary copy was lost (node death) so a
        lineage re-execution can write them again. Blocked getters keep
        waiting on the same entry and wake when the reconstructed value is
        sealed (reference: object_recovery_manager.h:68-94 — a lost object
        returns to 'pending' while its creating task is resubmitted)."""
        doomed_uris = []
        with self._lock:
            for oid in object_ids:
                entry = self._entries.get(oid)
                if entry is None:
                    continue
                if entry.freed or not entry.event.is_set():
                    # freed: accounting already settled, and a user-freed
                    # object must not be resurrected by reconstruction.
                    # unsealed: nothing to invalidate.
                    continue
                if entry.in_native and self._native is not None:
                    if entry.value is not None:
                        self._native.release(oid.hex())
                    self._native.delete(oid.hex())
                if entry.spilled_path is not None:
                    doomed_uris.append(entry.spilled_path)
                    if entry.value is None:
                        self._spilled_bytes -= entry.size_bytes
                    entry.spilled_path = None
                if not entry.in_native and (
                        entry.value is not None
                        or entry.serialized is not None):
                    self._total_bytes -= entry.size_bytes
                entry.value = None
                entry.serialized = None
                entry.deserialized = False
                entry.is_exception = False
                entry.freed = False
                entry.in_native = False
                entry.size_bytes = 0
                entry.pinned = False
                entry.remote_fetch = None
                entry.event.clear()
        for uri in doomed_uris:
            self._backend().delete(uri)

    def fail_all_pending(self, exc: BaseException) -> None:
        """Seal every unsealed entry with the given error (used at shutdown so
        blocked gets raise instead of hanging forever)."""
        fired = []
        with self._lock:
            for oid, entry in self._entries.items():
                if not entry.event.is_set():
                    entry.value = exc
                    entry.deserialized = True
                    entry.is_exception = True
                    entry.event.set()
                    cbs = self._take_seal_callbacks(entry)
                    if cbs:
                        fired.append((cbs, oid))
        for cbs, oid in fired:
            self._fire_seal_callbacks(cbs, oid)

    def evict_all(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total_bytes = 0

    def stats(self) -> dict:
        with self._lock:
            sealed = sum(1 for e in self._entries.values() if e.event.is_set())
            return {
                "num_objects": len(self._entries),
                "num_sealed": sealed,
                "total_serialized_bytes": self._total_bytes,
            }

    def record_metrics(self) -> None:
        """Refresh the resident-bytes gauge (metrics-agent collector)."""
        with self._lock:
            resident = self._total_bytes
        builtin_metrics.object_store_bytes().set(resident)
