"""Fenced cluster membership: epochs, leases, accrual suspicion.

The analog of the reference's GCS node manager + health check manager
(gcs_node_manager.cc registration/death bookkeeping,
gcs_health_check_manager.h liveness) with two upgrades the reference
also carries:

* **Epoch fencing** (reference: raylet restarts get a new node id; GCS
  rejects RPCs from dead incarnations). Every daemon registration mints
  a monotonically increasing ``node_epoch``; the epoch rides the wire-v9
  seq envelope and the resume handshake, so a daemon that was declared
  dead — then comes back from the other side of a partition — cannot
  re-attach its old session or replay stale frames. It gets a
  ``fenced`` reply and must re-register as a *new* incarnation; its old
  actors were declared dead exactly once when the lease expired.

* **Accrual suspicion** (Hayashibara et al.'s phi-accrual detector, the
  SWIM-family alternative to fixed ping/timeout): instead of "N missed
  pings at a fixed period", every piece of channel liveness — frame
  arrivals, acks, metrics_batch pushes, health pongs — feeds a per-node
  inter-arrival history, and the suspicion score is how improbable the
  current silence is *given that node's own observed cadence*. A node
  that routinely goes quiet for 10s during XLA compiles earns a long
  mean interval and is not falsely declared; a node that chattered
  every 50ms and went silent crosses the threshold in well under a
  second. A hard lease (``RAY_TPU_node_lease_s``) bounds detection from
  above no matter what the history says.

The head owns one :class:`MembershipTable`; each registered node gets a
:class:`NodeLiveness`. Death/join events fan out to in-process
subscribers (serve controller, train BackendExecutor) and to the
``membership`` pubsub channel, so consumers react to a push instead of
discovering death via their next failed RPC.
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

#: ln(10) — phi is a base-10 log-improbability (phi==9 means the
#: observed silence had probability ~1e-9 under the node's cadence).
_LN10 = math.log(10.0)


class AccrualDetector:
    """Simplified phi-accrual over an exponential inter-arrival model.

    ``record()`` feeds one liveness arrival; ``phi(now)`` returns the
    suspicion score for the silence since the last arrival:
    ``phi = t_silent / (mean_interval * ln 10)`` — the -log10 of the
    probability that an exponential process with the observed mean
    stays silent for ``t_silent``. The mean is clamped below by
    ``floor_s`` (the probe period) so a burst of sub-millisecond frame
    arrivals cannot make a routine 100ms pause look like death."""

    def __init__(self, window: int = 64, floor_s: float = 0.25):
        self._intervals: collections.deque = collections.deque(
            maxlen=window)
        self._floor = float(floor_s)
        self.last_arrival = time.monotonic()

    def record(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        gap = now - self.last_arrival
        if gap > 0:
            self._intervals.append(gap)
        self.last_arrival = now

    def mean_interval(self) -> float:
        if not self._intervals:
            return self._floor
        return max(self._floor,
                   sum(self._intervals) / len(self._intervals))

    def phi(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        silent = now - self.last_arrival
        if silent <= 0:
            return 0.0
        return silent / (self.mean_interval() * _LN10)


class NodeLiveness:
    """One node incarnation's liveness state at the head."""

    __slots__ = ("node_id_hex", "epoch", "detector", "soft_failures",
                 "registered_at")

    def __init__(self, node_id_hex: str, epoch: int,
                 probe_period_s: float = 0.25):
        self.node_id_hex = node_id_hex
        self.epoch = epoch
        self.detector = AccrualDetector(floor_s=probe_period_s)
        #: Consecutive soft probe failures (timeouts / blackholed sends)
        #: — evidence of partition, not process death. Reset on any
        #: arrival.
        self.soft_failures = 0
        self.registered_at = time.monotonic()

    def record_arrival(self, now: Optional[float] = None) -> None:
        self.detector.record(now)
        self.soft_failures = 0

    def silent_for(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        return now - self.detector.last_arrival

    def phi(self, now: Optional[float] = None) -> float:
        return self.detector.phi(now)


class MembershipTable:
    """Head-side membership: incarnation epochs, liveness, fan-out.

    Epochs are minted monotonically (persisted through the gcs_store's
    ``node_epochs`` table when one is attached, so a restarted head
    keeps fencing incarnations it registered in a previous life) and a
    declared death moves the epoch into the fenced set — a ``resume``
    or frame carrying a fenced epoch is dropped and counted, never
    applied."""

    def __init__(self, gcs_store=None):
        self._lock = threading.Lock()
        self._gcs_store = gcs_store
        self._epoch_counter = 0
        #: Rehydration accounting (head failover): the epoch floor
        #: inherited from previous head lives (every new epoch is
        #: minted strictly above it) and how many prior node
        #: incarnations the store remembered. Status/recovery surfaces
        #: read these; 0/0 on a first boot.
        self.recovered_epoch_floor = 0
        self.prior_node_count = 0
        if gcs_store is not None:
            self._epoch_counter = gcs_store.max_node_epoch()
            self.recovered_epoch_floor = self._epoch_counter
            self.prior_node_count = len(gcs_store.node_epochs)
        #: node_id hex -> live NodeLiveness (current incarnation only).
        self._live: Dict[str, NodeLiveness] = {}
        #: Epochs whose incarnation was declared dead: any frame or
        #: resume stamped with one of these is fenced.
        self._fenced_epochs: set = set()
        self._subscribers: List[Callable[[dict], None]] = []
        #: Monotonic event version (serve/train long-pollers compare it).
        self.version = 0

    # -- epochs ---------------------------------------------------------

    def mint_epoch(self, node_id_hex: str,
                   probe_period_s: float = 0.25) -> int:
        """Register a (new incarnation of a) node: next epoch, recorded
        durably when a gcs_store is attached."""
        with self._lock:
            self._epoch_counter += 1
            epoch = self._epoch_counter
            if self._gcs_store is not None:
                try:
                    self._gcs_store.record_node_epoch(node_id_hex, epoch)
                except OSError:
                    logger.exception("could not persist node epoch")
            self._live[node_id_hex] = NodeLiveness(
                node_id_hex, epoch, probe_period_s=probe_period_s)
            self.version += 1
        self._publish({"event": "joined", "node_id": node_id_hex,
                       "epoch": epoch})
        return epoch

    def current_epoch(self, node_id_hex: str) -> Optional[int]:
        with self._lock:
            live = self._live.get(node_id_hex)
            return live.epoch if live is not None else None

    def is_fenced(self, epoch: int) -> bool:
        """True for an epoch whose incarnation was DECLARED DEAD here.

        Deliberately narrow: an epoch this head never minted (a daemon
        re-registering across a head restart) is NOT fenced — that
        daemon's resident actors are exactly what the gcs_store rebind
        path exists to recover. Fencing targets one thing only: an
        incarnation whose lease this head expired coming back from the
        far side of a partition."""
        if epoch <= 0:
            return False  # 0 = epoch unknown/not yet learned
        with self._lock:
            return epoch in self._fenced_epochs

    def declare_dead(self, node_id_hex: str, reason: str = "") -> bool:
        """Fence the node's current incarnation. Returns True exactly
        once per incarnation — the caller runs the death cascade only
        on True, so a racing health sweep and channel-death handler
        cannot declare the same incarnation dead twice."""
        with self._lock:
            live = self._live.pop(node_id_hex, None)
            if live is None:
                return False
            self._fenced_epochs.add(live.epoch)
            self.version += 1
            epoch = live.epoch
        self._publish({"event": "dead", "node_id": node_id_hex,
                       "epoch": epoch, "reason": reason})
        return True

    # -- liveness -------------------------------------------------------

    def liveness(self, node_id_hex: str) -> Optional[NodeLiveness]:
        with self._lock:
            return self._live.get(node_id_hex)

    def snapshot(self) -> List[dict]:
        """Read-only view of every live incarnation for status surfaces
        (``/api/cluster_status``, ``ray-tpu status``/``top``): epoch,
        current phi suspicion, and the silence since the last liveness
        arrival."""
        with self._lock:
            live = list(self._live.values())
        now = time.monotonic()
        return [{"node_id": lv.node_id_hex,
                 "epoch": lv.epoch,
                 "phi": round(lv.phi(now), 3),
                 "last_heartbeat_age_s": round(lv.silent_for(now), 3),
                 "soft_failures": lv.soft_failures}
                for lv in live]

    def record_arrival(self, node_id_hex: str) -> None:
        live = self.liveness(node_id_hex)
        if live is not None:
            live.record_arrival()

    # -- fan-out --------------------------------------------------------

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        """In-process push subscription (serve controller, train
        BackendExecutor). ``fn`` runs on the publisher's thread — it
        must be quick and must not raise."""
        with self._lock:
            self._subscribers.append(fn)

    def unsubscribe(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            try:
                self._subscribers.remove(fn)
            except ValueError:
                pass

    def _publish(self, event: dict) -> None:
        with self._lock:
            subs = list(self._subscribers)
        for fn in subs:
            try:
                fn(dict(event))
            except Exception:  # noqa: BLE001 - one bad subscriber must
                # not break membership bookkeeping for the rest.
                logger.exception("membership subscriber failed")
