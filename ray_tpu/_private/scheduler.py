"""Resource accounting and admission control.

Single-node analog of the reference's two-level scheduler
(src/ray/raylet/scheduling/cluster_task_manager.h picks a node;
local_task_manager.h acquires resources and dispatches). Round 1 runs one
node, so this class does the local half: fixed-point-free float resource
vectors, placement-group bundle reservations (the 2-phase
Prepare/Commit collapses to one phase on a single node), and feasibility
checks so infeasible tasks error loudly instead of hanging.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ray_tpu._private.ids import PlacementGroupID
from ray_tpu.exceptions import PlacementGroupError

_EPS = 1e-9


def record_queue_depth(pending: int) -> None:
    """Refresh the ``ray_tpu_scheduler_pending_tasks`` gauge. The ready
    queues live with the runtime's dispatch loop, but the gauge belongs
    to the scheduler it describes; the runtime's metrics-agent collector
    calls this right before each export snapshot."""
    from ray_tpu._private import builtin_metrics
    builtin_metrics.scheduler_pending_tasks().set(pending)


def _fits(avail: Dict[str, float], need: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + _EPS >= v for k, v in need.items())


def _sub(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) - v


def _add(avail: Dict[str, float], need: Dict[str, float]) -> None:
    for k, v in need.items():
        avail[k] = avail.get(k, 0.0) + v


class _Bundle:
    def __init__(self, resources: Dict[str, float]):
        self.reserved = dict(resources)
        self.available = dict(resources)


class ResourceScheduler:
    def __init__(self, total: Dict[str, float]):
        self._lock = threading.Lock()
        self.total: Dict[str, float] = dict(total)
        self.available: Dict[str, float] = dict(total)
        self._placement_groups: Dict[PlacementGroupID, List[_Bundle]] = {}

    # -- feasibility ------------------------------------------------------

    def is_feasible(self, resources: Dict[str, float],
                    pg_id: Optional[PlacementGroupID] = None,
                    bundle_index: int = -1) -> bool:
        with self._lock:
            if pg_id is not None:
                bundles = self._placement_groups.get(pg_id)
                if bundles is None:
                    return False
                if bundle_index >= 0:
                    if bundle_index >= len(bundles):
                        return False
                    return _fits(bundles[bundle_index].reserved, resources)
                return any(_fits(b.reserved, resources) for b in bundles)
            return _fits(self.total, resources)

    # -- acquire/release --------------------------------------------------

    def try_acquire(self, resources: Dict[str, float],
                    pg_id: Optional[PlacementGroupID] = None,
                    bundle_index: int = -1) -> Optional[int]:
        """Acquire resources; returns the bundle index used (or -1 for the
        global pool), or None if not currently available."""
        with self._lock:
            if pg_id is not None:
                bundles = self._placement_groups.get(pg_id)
                if bundles is None:
                    return None
                if bundle_index >= len(bundles):
                    return None
                candidates = (
                    [bundle_index] if bundle_index >= 0
                    else range(len(bundles)))
                for i in candidates:
                    if _fits(bundles[i].available, resources):
                        _sub(bundles[i].available, resources)
                        return i
                return None
            if _fits(self.available, resources):
                _sub(self.available, resources)
                return -1
            return None

    def force_acquire(self, resources: Dict[str, float],
                      pg_id: Optional[PlacementGroupID] = None,
                      bundle_index: int = -1) -> None:
        """Acquire without availability check (may transiently overcommit).

        Used when a worker unblocks from a nested ``get`` and reclaims the
        resources it released while blocked — the analog of the reference's
        NotifyUnblocked path, where the raylet tolerates transient
        oversubscription rather than deadlocking."""
        with self._lock:
            if pg_id is not None:
                bundles = self._placement_groups.get(pg_id)
                if bundles is not None and 0 <= bundle_index < len(bundles):
                    _sub(bundles[bundle_index].available, resources)
                return
            _sub(self.available, resources)

    def release(self, resources: Dict[str, float],
                pg_id: Optional[PlacementGroupID] = None,
                bundle_index: int = -1) -> None:
        with self._lock:
            if pg_id is not None:
                bundles = self._placement_groups.get(pg_id)
                if bundles is not None and 0 <= bundle_index < len(bundles):
                    _add(bundles[bundle_index].available, resources)
                return
            _add(self.available, resources)

    # -- placement groups -------------------------------------------------

    def placement_groups(self) -> Dict[PlacementGroupID, List[Dict[str, float]]]:
        """Snapshot of reserved bundles per PG (state API)."""
        with self._lock:
            return {pg_id: [dict(b.reserved) for b in bundles]
                    for pg_id, bundles in self._placement_groups.items()}

    def create_placement_group(
            self, pg_id: PlacementGroupID,
            bundles: List[Dict[str, float]]) -> None:
        with self._lock:
            need: Dict[str, float] = {}
            for b in bundles:
                _add(need, b)
            if not _fits(self.total, need):
                raise PlacementGroupError(
                    f"Placement group bundles {bundles} are infeasible on this "
                    f"cluster (total resources {self.total}).")
            if not _fits(self.available, need):
                raise PlacementGroupError(
                    f"Placement group bundles {bundles} cannot be reserved now "
                    f"(available {self.available}). Round 1 has no wait queue "
                    "for PG creation.")
            _sub(self.available, need)
            self._placement_groups[pg_id] = [_Bundle(b) for b in bundles]

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            bundles = self._placement_groups.pop(pg_id, None)
            if bundles is None:
                return
            for b in bundles:
                _add(self.available, b.reserved)

    def placement_group_exists(self, pg_id: PlacementGroupID) -> bool:
        with self._lock:
            return pg_id in self._placement_groups

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "total": dict(self.total),
                "available": dict(self.available),
                "num_placement_groups": len(self._placement_groups),
            }
