"""Core ops/s microbenchmark suite.

Analog of the reference's release/microbenchmark harness
(python/ray/_private/ray_perf.py:93-163): measures the runtime's primitive
throughput/latency — task submission, actor calls, put/get — printing one
line per metric. Run via ``python -m ray_tpu._private.ray_perf`` or
``ray-tpu microbenchmark``.
"""

from __future__ import annotations

import sys
import time
from typing import Callable, Dict, List

import numpy as np

import ray_tpu


def timeit(name: str, fn: Callable[[], None], multiplier: int = 1,
           duration: float = 2.0) -> Dict[str, float]:
    """Run fn repeatedly for ~duration seconds; report ops/s."""
    # warmup
    fn()
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < duration:
        fn()
        count += 1
    dt = time.perf_counter() - start
    rate = count * multiplier / dt
    # Direct stdout write, not print(): _private/ modules stream task
    # output through the log subsystem and the lint bans bare print.
    sys.stdout.write(
        f"{name}: {rate:,.1f} ops/s ({count} iters in {dt:.2f}s)\n")
    return {"name": name, "ops_per_s": rate}


def main(duration: float = 2.0) -> List[Dict[str, float]]:
    if not ray_tpu.is_initialized():
        ray_tpu.init()
    results = []

    @ray_tpu.remote
    def noop():
        return 0

    @ray_tpu.remote
    def noop_arg(x):
        return x

    results.append(timeit(
        "single_task_latency",
        lambda: ray_tpu.get(noop.remote()), duration=duration))

    def batch_tasks():
        ray_tpu.get([noop.remote() for _ in range(100)])

    results.append(timeit("tasks_per_second", batch_tasks, multiplier=100,
                          duration=duration))

    data = ray_tpu.put(np.zeros(1024, np.float32))

    def tasks_with_arg():
        ray_tpu.get([noop_arg.remote(data) for _ in range(100)])

    results.append(timeit("tasks_with_shared_arg_per_second", tasks_with_arg,
                          multiplier=100, duration=duration))

    small = np.zeros(16, np.uint8)
    results.append(timeit(
        "put_small", lambda: ray_tpu.put(small), duration=duration))

    big = np.zeros(1 << 20, np.uint8)
    results.append(timeit(
        "put_1mb", lambda: ray_tpu.put(big), duration=duration))

    ref = ray_tpu.put(np.zeros(1 << 20, np.uint8))
    results.append(timeit(
        "get_1mb", lambda: ray_tpu.get(ref), duration=duration))

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    actor = Counter.remote()
    results.append(timeit(
        "actor_call_latency",
        lambda: ray_tpu.get(actor.incr.remote()), duration=duration))

    def actor_batch():
        ray_tpu.get([actor.incr.remote() for _ in range(100)])

    results.append(timeit("actor_calls_per_second", actor_batch,
                          multiplier=100, duration=duration))

    actors = [Counter.remote() for _ in range(8)]

    def scatter_calls():
        ray_tpu.get([a.incr.remote() for a in actors for _ in range(12)])

    results.append(timeit("actor_calls_8_actors_per_second", scatter_calls,
                          multiplier=96, duration=duration))
    for a in actors:
        ray_tpu.kill(a)
    ray_tpu.kill(actor)
    return results


if __name__ == "__main__":
    main()
