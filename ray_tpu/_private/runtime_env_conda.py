"""conda runtime-env plugin: per-environment worker interpreters.

Analog of the reference's ``_private/runtime_env/conda.py``: a
task/actor with ``runtime_env={"conda": ...}`` runs its worker process
under a conda environment's interpreter.

Two spec forms (matching the reference's):
* ``"conda": "<env-name>"`` — an EXISTING named environment; resolved to
  ``<conda base>/envs/<name>/bin/python`` via ``conda info --base``.
* ``"conda": {...}`` — an environment.yml-style dict; materialized once
  per content hash as ``ray_tpu_<hash>`` via ``conda env create`` and
  reused for the cluster's lifetime (the URI-cache pattern the pip/venv
  plugin follows, runtime_env_pip.ensure_venv).

The conda binary is discovered through ``$CONDA_EXE`` or PATH; images
without conda get a RuntimeEnvSetupError naming the missing dependency
instead of a silent fallback (this build environment ships no conda —
the tests drive the plugin with a fake binary).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import subprocess
import threading
from typing import Any, Dict, Optional, Union

from ray_tpu.exceptions import RuntimeEnvSetupError

_lock = threading.Lock()
_key_locks: Dict[str, threading.Lock] = {}
_ready: Dict[str, str] = {}   # spec key -> python executable
_base_cache: Optional[str] = None


def _conda_exe() -> str:
    exe = os.environ.get("CONDA_EXE") or shutil.which("conda")
    if not exe:
        raise RuntimeEnvSetupError(
            "runtime_env['conda'] requires the conda binary, which is "
            "not installed on this node (checked $CONDA_EXE and PATH). "
            "Install miniconda/miniforge, or use runtime_env['pip'] "
            "(venv-based) instead.")
    return exe


def _run(args, timeout=600) -> subprocess.CompletedProcess:
    return subprocess.run(args, capture_output=True, text=True,
                          timeout=timeout)


def _conda_base(exe: str) -> str:
    global _base_cache
    with _lock:
        if _base_cache is not None:
            return _base_cache
    proc = _run([exe, "info", "--base"], timeout=60)
    if proc.returncode != 0:
        raise RuntimeEnvSetupError(
            f"conda info --base failed: {proc.stderr[-500:]}")
    base = proc.stdout.strip().splitlines()[-1].strip()
    with _lock:
        _base_cache = base
    return base


def _env_python(base: str, name: str) -> str:
    return os.path.join(base, "envs", name, "bin", "python")


def spec_key(spec: Union[str, dict]) -> str:
    """Content hash of a dict spec — the cached env's name suffix."""
    return hashlib.sha1(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


def _write_environment_yaml(path: str, spec: dict) -> None:
    """Minimal environment.yml writer (no yaml dependency): name/
    channels/dependencies with string entries and the nested
    ``- pip: [...]`` block the reference's format allows."""
    lines = []
    if "name" in spec:
        lines.append(f"name: {spec['name']}")
    for section in ("channels", "dependencies"):
        entries = spec.get(section)
        if not entries:
            continue
        lines.append(f"{section}:")
        for e in entries:
            if isinstance(e, dict) and "pip" in e:
                lines.append("  - pip:")
                for p in e["pip"]:
                    lines.append(f"    - {p}")
            else:
                lines.append(f"  - {e}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def conda_python(spec: Union[str, dict]) -> str:
    """Resolve (and for dict specs, materialize) the environment;
    returns its python executable for worker spawning."""
    if isinstance(spec, str):
        exe = _conda_exe()
        python = _env_python(_conda_base(exe), spec)
        if not os.path.exists(python):
            raise RuntimeEnvSetupError(
                f"runtime_env['conda'] names environment {spec!r}, but "
                f"{python} does not exist. `conda env list` shows the "
                "available environments.")
        return python
    if not isinstance(spec, dict):
        raise RuntimeEnvSetupError(
            "runtime_env['conda'] must be an env name (str) or an "
            f"environment.yml-style dict, got {type(spec).__name__}")

    key = spec_key(spec)
    with _lock:
        cached = _ready.get(key)
        if cached is not None:
            return cached
        key_lock = _key_locks.setdefault(key, threading.Lock())
    with key_lock:
        with _lock:
            cached = _ready.get(key)
            if cached is not None:
                return cached
        exe = _conda_exe()
        base = _conda_base(exe)
        name = f"ray_tpu_{key}"
        python = _env_python(base, name)
        if not os.path.exists(python):
            import tempfile
            with tempfile.NamedTemporaryFile(
                    "w", suffix=".yml", delete=False) as f:
                yml = f.name
            try:
                _write_environment_yaml(yml, dict(spec, name=name))
                proc = _run([exe, "env", "create", "-n", name,
                             "-f", yml])
                if proc.returncode != 0:
                    raise RuntimeEnvSetupError(
                        f"conda env create for runtime_env failed: "
                        f"{proc.stderr[-2000:]}")
            finally:
                os.unlink(yml)
            if not os.path.exists(python):
                raise RuntimeEnvSetupError(
                    f"conda env create reported success but {python} "
                    "does not exist")
        with _lock:
            _ready[key] = python
        return python


def interpreter_matches(spec: Union[str, dict]) -> bool:
    """True iff THIS process already runs under the environment the
    spec names — the in-process check runtime_env.setup uses inside
    worker processes (no conda binary needed there)."""
    import sys
    # The spawn path, NOT realpath: conda env pythons may be symlinks
    # to a shared interpreter, and the env identity lives in the path
    # the worker was launched under.
    exe = sys.executable
    if isinstance(spec, str):
        return f"{os.sep}envs{os.sep}{spec}{os.sep}" in exe
    if isinstance(spec, dict):
        name = f"ray_tpu_{spec_key(spec)}"
        return f"{os.sep}envs{os.sep}{name}{os.sep}" in exe
    return False
