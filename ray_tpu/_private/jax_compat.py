"""Version compatibility shims for jax APIs the kernels lean on.

``shard_map`` moved from ``jax.experimental.shard_map`` to top-level
``jax.shard_map`` (and renamed its replication check ``check_rep`` ->
``check_vma``) around jax 0.6. Kernel code imports the new spelling
from here so it runs on both sides of the move.
"""

from __future__ import annotations

try:
    from jax import shard_map  # noqa: F401 - jax >= 0.6
except ImportError:
    from jax.experimental.shard_map import shard_map as _shard_map_exp

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _shard_map_exp(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma)
