"""Resource-usage sync: the Ray-syncer analog.

Analog of the reference's ``common/ray_syncer/ray_syncer.h:88``: each
node owns a set of *components* (resource load, object-store usage,
memory) whose snapshots carry **per-component version numbers**; only
CHANGED snapshots are shipped, and a receiver applies a message only
when its version is newer than the last applied one for that
(node, component) — stale or duplicated deliveries are dropped, counted,
and harmless, so the transport needs no ordering guarantees beyond
"eventually delivers something recent".

Topology (matching the head/daemon wire protocol in multinode.py rather
than the reference's raylet-mesh gRPC streams): daemons piggyback their
changed snapshots on health-channel pong frames (tiny, periodic, never
queued behind data transfers), and the head piggybacks its aggregated
**cluster digest** on ping frames — so every daemon converges on a view
of cluster-wide resource usage without a second connection, and the head
stops being the only process that can answer "what is the cluster
doing" (the resource-gossip role of ``GrpcBasedResourceBroadcaster``).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

# Component names (reference: ray_syncer MessageType RESOURCE_VIEW /
# COMMANDS; ours are usage-oriented).
RESOURCE_LOAD = "resource_load"
OBJECT_STORE = "object_store"
MEMORY = "memory"
#: Daemon-local dispatch backlog (reference: the raylet reports its
#: per-class queue depth as resource demand for scheduling/autoscaling).
BACKLOG = "backlog"


class NodeSyncReporter:
    """Daemon-side: collects component snapshots and emits only the
    changed ones, each under a monotonically increasing version."""

    def __init__(self) -> None:
        self._collectors: Dict[str, Callable[[], Optional[dict]]] = {}
        self._versions: Dict[str, int] = {}
        self._last_payload: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def register(self, component: str,
                 collect: Callable[[], Optional[dict]]) -> None:
        with self._lock:
            self._collectors[component] = collect

    def reset_peer(self) -> None:
        """Forget what the peer has seen (head restarted): every
        component re-ships its current snapshot on the next poll, under
        a BUMPED version — the new head must not drop it as stale."""
        with self._lock:
            self._last_payload.clear()

    def poll(self) -> List[dict]:
        """Collect every component; emit {component, version, payload}
        for the ones whose payload changed since the last emit. A
        collector returning None (or raising) is skipped this round —
        a flaky gauge must not kill the health channel."""
        out: List[dict] = []
        with self._lock:
            for comp, collect in self._collectors.items():
                try:
                    payload = collect()
                except Exception:  # noqa: BLE001 - gauge failure != death
                    continue
                if payload is None:
                    continue
                if self._last_payload.get(comp) == payload:
                    continue
                version = self._versions.get(comp, 0) + 1
                self._versions[comp] = version
                self._last_payload[comp] = payload
                out.append({"component": comp, "version": version,
                            "payload": payload})
        return out


class ClusterSyncState:
    """Receiver + aggregator: versioned only-newer application per
    (node, component), and a cluster digest for gossip-back."""

    def __init__(self) -> None:
        self._applied: Dict[Tuple[str, str], int] = {}
        self._view: Dict[str, Dict[str, dict]] = {}
        self._lock = threading.Lock()
        self.stale_drops = 0
        self._digest_version = 0

    def apply(self, node_id: str, messages: List[dict]) -> int:
        """Apply a batch from one node; returns how many were NEW.
        Messages at or below the last applied version are dropped."""
        applied = 0
        with self._lock:
            for msg in messages:
                comp = msg["component"]
                key = (node_id, comp)
                if msg["version"] <= self._applied.get(key, 0):
                    self.stale_drops += 1
                    continue
                self._applied[key] = msg["version"]
                self._view.setdefault(node_id, {})[comp] = msg["payload"]
                applied += 1
            if applied:
                self._digest_version += 1
        return applied

    def remove_node(self, node_id: str) -> None:
        with self._lock:
            self._view.pop(node_id, None)
            for key in [k for k in self._applied if k[0] == node_id]:
                del self._applied[key]
            self._digest_version += 1

    def view(self) -> Dict[str, Dict[str, dict]]:
        with self._lock:
            return {n: dict(comps) for n, comps in self._view.items()}

    def digest(self) -> dict:
        """The gossip-back payload: per-node usage plus cluster totals,
        stamped with a version so daemons can apply only-newer too."""
        with self._lock:
            totals: Dict[str, float] = {}
            for comps in self._view.values():
                load = comps.get(RESOURCE_LOAD, {})
                for name, amt in load.get("available", {}).items():
                    totals[name] = totals.get(name, 0.0) + float(amt)
            return {"version": self._digest_version,
                    "nodes": {n: dict(comps)
                              for n, comps in self._view.items()},
                    "available_total": totals}


class DigestCache:
    """Daemon-side holder of the head's cluster digest (only-newer)."""

    def __init__(self) -> None:
        self._digest: Optional[dict] = None
        self._lock = threading.Lock()

    def apply(self, digest: Optional[dict]) -> bool:
        if not digest:
            return False
        with self._lock:
            if self._digest is not None and \
                    digest.get("version", 0) <= \
                    self._digest.get("version", 0):
                return False
            self._digest = digest
            return True

    def reset(self) -> None:
        """New head epoch (reconnect): any incoming version is newer."""
        with self._lock:
            self._digest = None

    def get(self) -> Optional[dict]:
        with self._lock:
            return self._digest
