"""Instrumented event substrate: per-handler latency/queue statistics.

Analog of the reference's ``common/asio/instrumented_io_context`` +
``common/event_stats.cc``: every handler class the control plane runs —
head completion callbacks, health sweeps, accept/handshake, dispatch —
records queue wait and run time under its name, and the aggregate view
(count / total / mean / max / p50 / p99) is queryable at runtime (the
reference prints it via ``RAY_event_stats``; here it feeds the
dashboard's ``/api/event_stats`` and ``HeadServer.event_stats()``).

Recording is lock-cheap (one mutex per named handler, ring buffer of
recent samples for percentiles) and always-on: the reference gates on a
flag because gRPC handler counts are huge; this control plane's handler
rate is thread-scale, where the overhead is noise.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

#: ring-buffer size per handler for percentile estimates.
_WINDOW = 512


class _HandlerStats:
    __slots__ = ("count", "total_run_s", "max_run_s", "total_queue_s",
                 "max_queue_s", "recent_run_s", "lock")

    def __init__(self) -> None:
        self.count = 0
        self.total_run_s = 0.0
        self.max_run_s = 0.0
        self.total_queue_s = 0.0
        self.max_queue_s = 0.0
        self.recent_run_s: List[float] = []
        self.lock = threading.Lock()

    def record(self, run_s: float, queue_s: float = 0.0) -> None:
        with self.lock:
            self.count += 1
            self.total_run_s += run_s
            self.max_run_s = max(self.max_run_s, run_s)
            self.total_queue_s += queue_s
            self.max_queue_s = max(self.max_queue_s, queue_s)
            self.recent_run_s.append(run_s)
            del self.recent_run_s[:-_WINDOW]

    def summary(self) -> Dict[str, Any]:
        with self.lock:
            recent = sorted(self.recent_run_s)
            count = self.count

            def pct(p: float) -> float:
                if not recent:
                    return 0.0
                idx = min(int(p * len(recent)), len(recent) - 1)
                return recent[idx]

            return {
                "count": count,
                "total_run_ms": round(self.total_run_s * 1e3, 3),
                "mean_run_ms": round(
                    self.total_run_s / count * 1e3, 3) if count else 0.0,
                "max_run_ms": round(self.max_run_s * 1e3, 3),
                "p50_run_ms": round(pct(0.50) * 1e3, 3),
                "p99_run_ms": round(pct(0.99) * 1e3, 3),
                "total_queue_ms": round(self.total_queue_s * 1e3, 3),
                "max_queue_ms": round(self.max_queue_s * 1e3, 3),
            }


class EventStats:
    """Named-handler stats registry; one global instance serves the
    whole process (the reference's per-io_context split collapses —
    this control plane runs on threads, not loops)."""

    def __init__(self) -> None:
        self._handlers: Dict[str, _HandlerStats] = {}
        self._lock = threading.Lock()

    def _of(self, name: str) -> _HandlerStats:
        with self._lock:
            st = self._handlers.get(name)
            if st is None:
                st = self._handlers[name] = _HandlerStats()
            return st

    def record(self, name: str, run_s: float,
               queue_s: float = 0.0) -> None:
        self._of(name).record(run_s, queue_s)

    def timed(self, name: str):
        """Context manager timing a handler body."""
        return _Timed(self, name)

    def wrap(self, name: str, fn: Callable,
             queued_at: Optional[float] = None) -> Callable:
        """Wrap a callable for deferred execution (thread pools): queue
        wait runs from ``queued_at`` (or wrap time) to invocation."""
        q0 = time.monotonic() if queued_at is None else queued_at

        def run(*args, **kwargs):
            start = time.monotonic()
            try:
                return fn(*args, **kwargs)
            finally:
                end = time.monotonic()
                self.record(name, end - start, start - q0)

        return run

    def summary(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            names = list(self._handlers)
        return {name: self._of(name).summary() for name in names}

    def reset(self) -> None:
        with self._lock:
            self._handlers.clear()


class _Timed:
    __slots__ = ("_stats", "_name", "_t0")

    def __init__(self, stats: EventStats, name: str):
        self._stats = stats
        self._name = name

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._stats.record(self._name, time.monotonic() - self._t0)


#: process-global registry (reference: the RAY_event_stats singleton).
GLOBAL = EventStats()
