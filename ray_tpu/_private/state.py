"""Timeline + memory summaries.

Analog of the reference's python/ray/_private/state.py (timeline :851,
chrome_tracing_dump :435, memory_summary via internal_api): converts the
runtime's task-event buffer into chrome://tracing JSON and renders object-
store summaries for the CLI.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

from ray_tpu._private.worker import global_worker


def timeline(filename: Optional[str] = None) -> List[Dict[str, Any]]:
    """Chrome-tracing events (phase X) for every RUNNING→FINISHED/FAILED
    task pair. Load the output in chrome://tracing or Perfetto."""
    rt = global_worker.runtime
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    starts: Dict[str, Dict[str, Any]] = {}
    trace: List[Dict[str, Any]] = []
    for ev in rt.task_events():
        if ev["status"] == "RUNNING":
            starts[ev["task_id"]] = ev
        elif ev["status"] in ("FINISHED", "FAILED"):
            start = starts.pop(ev["task_id"], None)
            if start is None:
                continue
            trace.append({
                "cat": "task",
                "name": ev["name"],
                "ph": "X",
                "ts": start["time"] * 1e6,
                "dur": (ev["time"] - start["time"]) * 1e6,
                "pid": "ray_tpu",
                "tid": ev["task_id"][:8],
                "args": {"status": ev["status"]},
            })
    # Cross-process spans: the head's own tracing buffer plus worker/
    # daemon spans shipped over the metrics pipeline — one timeline for
    # the whole cluster.
    from ray_tpu.util import tracing as _tracing
    trace.extend(_tracing.export_chrome_trace())
    spans_fn = getattr(rt, "cluster_chrome_spans", None)
    if spans_fn is not None:
        trace.extend(spans_fn())
    # Flow events (ph s/f) drawn between parent and child spans whose
    # origins differ — the arrows that make a cross-process trace legible
    # in Perfetto instead of disconnected slices.
    flows_fn = getattr(rt, "trace_flow_events", None)
    if flows_fn is not None:
        trace.extend(flows_fn())
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace


def memory_summary() -> str:
    rt = global_worker.runtime
    if rt is None:
        raise RuntimeError("ray_tpu is not initialized")
    stats = rt.store.stats()
    lines = [
        "Object store summary:",
        f"  objects: {stats['num_objects']} "
        f"(sealed: {stats['num_sealed']})",
        f"  serialized bytes: {stats['total_serialized_bytes']}",
    ]
    if rt.store.native is not None:
        lines.append(
            f"  shm arena: {rt.store.native.num_objects()} objects, "
            f"{rt.store.native.used_bytes()} / "
            f"{rt.store.native.capacity} bytes")
    return "\n".join(lines)


def status_summary() -> str:
    import ray_tpu
    total = ray_tpu.cluster_resources()
    avail = ray_tpu.available_resources()
    lines = ["Resources:"]
    for k in sorted(total):
        lines.append(f"  {k}: {avail.get(k, 0):g} / {total[k]:g} available")
    from ray_tpu.experimental.state.api import summarize_tasks
    summary = summarize_tasks()
    lines.append(f"Tasks: {summary['total']} total "
                 f"{summary['by_state']}")
    # Synced per-node usage (the ray-syncer view), when daemons report.
    usage = ray_tpu.cluster_usage()
    if usage.get("nodes"):
        lines.append("Node usage (synced):")
        for node_id, comps in sorted(usage["nodes"].items()):
            load = comps.get("resource_load", {})
            store = comps.get("object_store", {})
            mem = comps.get("memory", {})
            parts = []
            if load:
                avail_cpu = load.get("available", {}).get("CPU")
                total_cpu = load.get("total", {}).get("CPU")
                if total_cpu is not None:
                    parts.append(f"CPU {avail_cpu:g}/{total_cpu:g}")
                parts.append(f"inflight={load.get('inflight_tasks', 0)}")
                parts.append(f"actors={load.get('actors', 0)}")
            if store:
                parts.append(
                    f"store={store.get('bytes', 0) / 1e6:.1f}MB/"
                    f"{store.get('objects', 0)}obj")
            if mem.get("rss_bytes"):
                parts.append(f"rss={mem['rss_bytes'] / 1e6:.0f}MB")
            backlog = comps.get("backlog", {})
            if backlog.get("queued") or backlog.get("temp_slots"):
                # Daemon-LOCAL dispatch queues (round 5): depth the
                # daemon owns, observed — not managed — by the head.
                # Temp slots show even at queued=0 (a drained queue
                # with lent capacity still running is the interesting
                # divergence).
                parts.append(f"backlog={backlog.get('queued', 0)}"
                             + (f"(+{backlog['temp_slots']}tmp)"
                                if backlog.get("temp_slots") else ""))
            lines.append(f"  {node_id[:12]}: " + " ".join(parts))
    # Head incarnation + last failover recovery (gcs_store-backed):
    # "which head life is this, and what did it replay coming up".
    rt = global_worker.runtime
    head_fn = getattr(rt, "head_recovery_info", None)
    if head_fn is not None:
        try:
            head = head_fn()
        except Exception:  # noqa: BLE001 - status must still answer
            head = None
        if head and head.get("incarnation"):
            line = f"Head: incarnation={head['incarnation']}"
            rec = head.get("last_recovery")
            if rec:
                import time as _time
                replayed = sum((rec.get("replayed") or {}).values())
                when = _time.strftime(
                    "%Y-%m-%d %H:%M:%S",
                    _time.localtime(rec.get("at", 0)))
                line += (f" last_recovery(at={when} "
                         f"epoch_floor={rec.get('epoch_floor', 0)} "
                         f"replayed={replayed}")
                if rec.get("corrupt_records"):
                    line += f" corrupt={rec['corrupt_records']}"
                line += ")"
            lines.append(line)
    # Membership internals (PR 11), read-only: incarnation epoch, phi
    # suspicion, and the silence since the last liveness arrival.
    snap_fn = getattr(rt, "membership_snapshot", None)
    rows = snap_fn() if snap_fn is not None else []
    if rows:
        lines.append("Membership:")
        for row in sorted(rows, key=lambda r: r["node_id"]):
            lines.append(
                f"  {row['node_id'][:12]}: epoch={row['epoch']} "
                f"phi={row['phi']:.2f} "
                f"hb_age={row['last_heartbeat_age_s']:.1f}s"
                + (f" soft_failures={row['soft_failures']}"
                   if row.get("soft_failures") else ""))
    # Serve deployments: target-vs-actual replicas straight from the
    # signal plane, so a scale-up in flight is visible as target>actual.
    serve_fn = getattr(rt, "serve_stats", None)
    if serve_fn is not None:
        try:
            deployments = serve_fn().get("deployments", {})
        except Exception:  # noqa: BLE001 - status must still answer
            deployments = {}
        if deployments:
            lines.append("Serve:")
            for name, d in sorted(deployments.items()):
                target = d.get("target_replicas")
                lines.append(
                    f"  {name}: replicas={d.get('replicas', 0)}"
                    + ("" if target is None else f" target={target}")
                    + f" qps={d.get('qps', 0.0):.2f}"
                    f" p95={d.get('p95_s', 0.0) * 1000:.1f}ms"
                    f" queue={d.get('mean_queue_depth', 0.0):.1f}")
    # Firing alerts (alerting plane): `ray-tpu status` answers "is the
    # cluster healthy" without a dashboard round-trip.
    alerts_fn = getattr(rt, "alerts_snapshot", None)
    if alerts_fn is not None:
        try:
            firing = alerts_fn().get("firing", [])
        except Exception:  # noqa: BLE001 - status must still answer
            firing = []
        if firing:
            lines.append(f"Alerts firing ({len(firing)}):")
            for a in firing:
                key = f"[{a['key']}]" if a.get("key") else ""
                lines.append(
                    f"  {a['rule']}{key}: {a.get('severity', '')} "
                    f"value={a.get('value', 0):.4g} "
                    f"for {a.get('since_s', 0):.0f}s")
    return "\n".join(lines)
