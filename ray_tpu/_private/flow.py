"""Dataplane flow observability: per-transfer ledger + head-side
per-link bandwidth matrix.

The cluster already counts transfer bytes as ONE scalar
(``ray_tpu_object_transfer_bytes_total{direction}``) — enough to know
the dataplane moved data, useless for knowing *which link* carried it.
This module is the missing accounting (reference: Ray's object manager
keeps exactly this per-transfer bookkeeping inside its pull manager /
PushManager to drive pull scheduling):

* :class:`FlowRecorder` — one per process, passive (no thread). Every
  object transfer the dataplane completes (pull, chunked pull, ranged
  serve, spill restore) calls :meth:`FlowRecorder.record` with one
  typed flow record ``{key, bytes, src, dst, duration, chunks,
  parallelism, failovers, tier, outcome}``. Records buffer in a
  bounded deque and ship on the existing metrics cadence as additive
  ``flow_batch`` push frames (same drain/refund contract as PR 14's
  profile windows: a failed publish refunds the records, drops are
  counted in ``ray_tpu_flow_batches_dropped_total``). The recorder is
  ALSO the single place the cluster-scalar fast counters
  (``record_transfer_in/out``, ``record_pull_chunks``) get bumped —
  an AST lint bans those calls elsewhere in ``_private/`` so future
  dataplane paths cannot silently bypass the ledger.

* :class:`FlowStore` — head-side aggregate (bounded, membership-aware
  like ProfileStore): a per-link matrix keyed ``(src_node, dst_node)``
  with windowed MB/s, p95 transfer latency, chunk/failover/error
  counts, plus a per-object fan-out table surfacing broadcast
  amplification (one object pulled by N nodes = the O(N) sends a
  tree broadcast would collapse). The store synthesizes queryable
  series into the head's :class:`TimeSeriesStore` —
  ``ray_tpu_transfer_link_bytes_total{src,dst}`` (+ chunk/failover
  counters), ``ray_tpu_transfer_link_mbps{link}``,
  ``ray_tpu_transfer_link_stalled{link}`` and
  ``ray_tpu_object_fanout_nodes{key}`` — restamped every publish tick
  (zero when idle) so the ``slow_link`` / ``hot_object_fanout`` alert
  rules both fire AND resolve promptly.

Attribution: the PULLER knows both ends of a transfer (its own node +
the holder address it pulled from), so link cells are built from
pull-side records; ``FlowStore.note_node`` learns each node's object
server address at registration to resolve ``host:port`` → node id.
Serve-side records carry only the peer's ephemeral port, so they
aggregate into per-node egress totals instead of inventing half-blind
matrix cells.

Knobs (``RAY_TPU_FLOW_*`` env > runtime flag table > default):
``flow_max_records`` (per-process buffer, 0 disables recording),
``flow_window_s``, ``flow_max_links``, ``flow_max_objects``,
``flow_slow_link_mbps``, ``flow_fanout_nodes``.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_MAX_RECORDS = 4096
DEFAULT_WINDOW_S = 60.0
DEFAULT_MAX_LINKS = 512
DEFAULT_MAX_OBJECTS = 512
DEFAULT_SLOW_LINK_MBPS = 1.0
DEFAULT_FANOUT_NODES = 8
#: Dead-node link state is evicted this long after the death push
#: (matches ProfileStore/TimeSeriesStore staleness semantics).
DEFAULT_STALENESS_S = 30.0

TIERS = ("replica", "spill", "inline", "push")
OUTCOMES = ("ok", "error")


def _cfg(env: str, flag: str, default):
    """Env spelling first (documented RAY_TPU_FLOW_*), then the live
    flag table (runtime config > env > default) — the same precedence
    every observability plane uses."""
    raw = os.environ.get(env, "")
    if raw:
        try:
            return type(default)(float(raw)) if not isinstance(
                default, str) else raw
        except (TypeError, ValueError):
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return runtime_config_value(flag, default)


def configured_max_records() -> int:
    return int(_cfg("RAY_TPU_FLOW_MAX_RECORDS", "flow_max_records",
                    DEFAULT_MAX_RECORDS))


def configured_window_s() -> float:
    return float(_cfg("RAY_TPU_FLOW_WINDOW_S", "flow_window_s",
                      DEFAULT_WINDOW_S))


def configured_max_links() -> int:
    return int(_cfg("RAY_TPU_FLOW_MAX_LINKS", "flow_max_links",
                    DEFAULT_MAX_LINKS))


def configured_max_objects() -> int:
    return int(_cfg("RAY_TPU_FLOW_MAX_OBJECTS", "flow_max_objects",
                    DEFAULT_MAX_OBJECTS))


def configured_slow_link_mbps() -> float:
    return float(_cfg("RAY_TPU_FLOW_SLOW_LINK_MBPS",
                      "flow_slow_link_mbps", DEFAULT_SLOW_LINK_MBPS))


def configured_fanout_nodes() -> int:
    return int(_cfg("RAY_TPU_FLOW_FANOUT_NODES", "flow_fanout_nodes",
                    DEFAULT_FANOUT_NODES))


def _addr_str(addr) -> str:
    if not addr:
        return ""
    if isinstance(addr, (tuple, list)) and len(addr) == 2:
        return f"{addr[0]}:{addr[1]}"
    return str(addr)


# ---------------------------------------------------------------------------
# Per-process recorder
# ---------------------------------------------------------------------------


class FlowRecorder:
    """Bounded per-process transfer ledger with drain/refund shipping
    semantics. Passive: no thread, no timer — the process's existing
    MetricsAgent drains it on the export cadence."""

    def __init__(self, max_records: Optional[int] = None):
        self.max_records = (configured_max_records()
                            if max_records is None else int(max_records))
        self.enabled = self.max_records > 0
        self._lock = threading.Lock()
        self._records: deque = deque()
        self.dropped = 0  # records squeezed out by the buffer bound
        self._inflight = 0  # bytes currently mid-transfer (pull side)

    # -- in-flight gauge ------------------------------------------------

    def begin(self, nbytes: int) -> None:
        """A transfer of ``nbytes`` entered flight (admission granted)."""
        with self._lock:
            self._inflight += max(0, int(nbytes))
        self._set_inflight_gauge()

    def end(self, nbytes: int) -> None:
        with self._lock:
            self._inflight = max(0, self._inflight - max(0, int(nbytes)))
        self._set_inflight_gauge()

    def _set_inflight_gauge(self) -> None:
        try:
            from ray_tpu._private import builtin_metrics
            builtin_metrics.transfer_inflight_bytes().set(self._inflight)
        except Exception:  # noqa: BLE001 - accounting must not fail a pull
            pass

    @property
    def inflight_bytes(self) -> int:
        with self._lock:
            return self._inflight

    # -- the ledger -----------------------------------------------------

    def record(self, *, key: str, nbytes: int, duration_s: float,
               direction: str, peer: Any = None, chunks: int = 1,
               parallelism: int = 1, failovers: int = 0,
               tier: str = "replica", outcome: str = "ok") -> None:
        """One completed (or terminally failed) object transfer.

        This is the SINGLE place the cluster-scalar transfer fast
        counters get bumped (lint-enforced), so the per-link ledger and
        the existing ``object_transfer_bytes`` metric can never drift
        apart. Failed transfers land in the ledger with
        ``outcome="error"`` but bump no byte counters — no bytes moved.
        """
        if tier not in TIERS:
            raise ValueError(f"unknown flow tier {tier!r} "
                             f"(one of {', '.join(TIERS)})")
        if outcome not in OUTCOMES:
            raise ValueError(f"unknown flow outcome {outcome!r} "
                             f"(one of {', '.join(OUTCOMES)})")
        nbytes = int(nbytes)
        chunks = max(1, int(chunks))
        if outcome == "ok":
            try:
                from ray_tpu._private import builtin_metrics
                if direction == "in":
                    builtin_metrics.record_transfer_in(nbytes)
                    if chunks > 1:
                        builtin_metrics.record_pull_chunks(chunks)
                else:
                    builtin_metrics.record_transfer_out(nbytes)
            except Exception:  # noqa: BLE001 - accounting only
                pass
        if not self.enabled:
            return
        peer_s = _addr_str(peer)
        rec = {
            "key": str(key),
            "bytes": nbytes,
            "src": peer_s if direction == "in" else "",
            "dst": peer_s if direction == "out" else "",
            "duration": float(max(0.0, duration_s)),
            "chunks": chunks,
            "parallelism": max(1, int(parallelism)),
            "failovers": max(0, int(failovers)),
            "tier": tier,
            "direction": direction,
            "outcome": outcome,
        }
        with self._lock:
            self._records.append(rec)
            while len(self._records) > self.max_records:
                self._records.popleft()
                self.dropped += 1

    def drain(self) -> Optional[List[dict]]:
        """Return-and-clear the buffered records (``None`` when empty)."""
        with self._lock:
            if not self._records:
                return None
            out = list(self._records)
            self._records.clear()
        return out

    def refund(self, records: List[dict]) -> None:
        """Put a failed publish's records back at the FRONT so order is
        kept; the bound still applies (oldest squeezed out, counted)."""
        if not records:
            return
        with self._lock:
            self._records.extendleft(reversed(records))
            while len(self._records) > self.max_records:
                self._records.popleft()
                self.dropped += 1

    def stats(self) -> dict:
        with self._lock:
            return {"buffered": len(self._records),
                    "dropped": self.dropped,
                    "inflight_bytes": self._inflight,
                    "enabled": self.enabled,
                    "max_records": self.max_records}


_recorder_lock = threading.Lock()
_recorder: Optional[FlowRecorder] = None


def global_flow_recorder() -> FlowRecorder:
    """The process-wide recorder (created on first use; recording is a
    no-op beyond the fast counters when ``flow_max_records <= 0``)."""
    global _recorder
    rec = _recorder
    if rec is None:
        with _recorder_lock:
            rec = _recorder
            if rec is None:
                rec = _recorder = FlowRecorder()
    return rec


def set_enabled(enabled: bool) -> None:
    """Flip recording live (bench on/off arms; the buffer is kept)."""
    rec = global_flow_recorder()
    rec.enabled = bool(enabled) and rec.max_records > 0


def shutdown_flow_recorder() -> None:
    """Drop the singleton (tests re-reading knobs)."""
    global _recorder
    with _recorder_lock:
        _recorder = None


# ---------------------------------------------------------------------------
# Head-side store
# ---------------------------------------------------------------------------


class _Link:
    """One directed matrix cell (src_node -> dst_node)."""

    __slots__ = ("bytes_total", "records_total", "chunks_total",
                 "failovers_total", "errors_total", "samples",
                 "last_seen", "dead_at")

    def __init__(self):
        self.bytes_total = 0
        self.records_total = 0
        self.chunks_total = 0
        self.failovers_total = 0
        self.errors_total = 0
        #: (t, bytes, duration_s) per record, trimmed to the window.
        self.samples: deque = deque()
        self.last_seen = time.monotonic()
        self.dead_at: Optional[float] = None

    def trim(self, now: float, window: float) -> None:
        cutoff = now - window
        while self.samples and self.samples[0][0] < cutoff:
            self.samples.popleft()

    def windowed(self, now: float, window: float) -> Tuple[int, float]:
        """(window_bytes, mbps) over ``window`` seconds."""
        self.trim(now, window)
        wbytes = sum(s[1] for s in self.samples)
        return wbytes, (wbytes / window) / (1024.0 * 1024.0)

    def p95_s(self) -> float:
        durs = sorted(s[2] for s in self.samples)
        if not durs:
            return 0.0
        return durs[min(len(durs) - 1, int(0.95 * len(durs)))]


class _ObjectFanout:
    """One object's pull fan-out: which nodes pulled it, how much."""

    __slots__ = ("nodes", "bytes_total", "pulls", "last_seen")

    def __init__(self):
        self.nodes: Dict[str, float] = {}  # dst node -> last pull ts
        self.bytes_total = 0
        self.pulls = 0
        self.last_seen = time.monotonic()

    def fanout(self, now: float, window: float) -> int:
        cutoff = now - window
        return sum(1 for t in self.nodes.values() if t >= cutoff)


class FlowStore:
    """Bounded head-side aggregation of flow records into a per-link
    matrix + per-object fan-out table, with membership-driven eviction
    and TimeSeriesStore series synthesis."""

    #: Minimum seconds between series publishes on the passive
    #: (ClusterMetrics.update) path; flow-batch arrivals publish
    #: immediately.
    PUBLISH_MIN_INTERVAL_S = 1.0

    def __init__(self, window_s: Optional[float] = None,
                 max_links: Optional[int] = None,
                 max_objects: Optional[int] = None,
                 staleness: float = DEFAULT_STALENESS_S,
                 slow_link_mbps: Optional[float] = None):
        self.window_s = max(1.0, configured_window_s()
                            if window_s is None else float(window_s))
        self.max_links = (configured_max_links() if max_links is None
                          else int(max_links))
        self.max_objects = (configured_max_objects()
                            if max_objects is None else int(max_objects))
        self.staleness = staleness
        self.slow_link_mbps = (configured_slow_link_mbps()
                               if slow_link_mbps is None
                               else float(slow_link_mbps))
        self._lock = threading.Lock()
        self._links: "OrderedDict[Tuple[str, str], _Link]" = OrderedDict()
        self._objects: "OrderedDict[str, _ObjectFanout]" = OrderedDict()
        #: object-server "host:port" -> node id hex (taught by the
        #: runtime at node registration; the puller records addresses).
        self._addr_to_node: Dict[str, str] = {}
        #: per-node egress/ingress byte totals (serve-side records land
        #: here — the server only knows the peer's ephemeral port).
        self._egress: Dict[str, int] = {}
        self._ingress: Dict[str, int] = {}
        self.dropped_links = 0
        self.dropped_objects = 0
        self.batches = 0
        self.records = 0
        self._last_publish = 0.0
        #: gauge label sets stamped last publish — restamped to 0 once
        #: after going idle so alert groups resolve instead of pinning
        #: on a stale last value.
        self._published_links: set = set()
        self._published_keys: set = set()
        #: The most recent broadcast's spanning tree (runtime-taught at
        #: broadcast completion; `ray-tpu xfer --tree` joins its edges
        #: against the link matrix for per-edge MB/s).
        self._last_broadcast: Optional[dict] = None

    def note_broadcast(self, tree: dict) -> None:
        """Record the spanning tree of a completed push broadcast
        ({key, size, fanout, depth, root, edges=[{src, dst, ok,
        failovers}...]})."""
        with self._lock:
            self._last_broadcast = dict(tree, recorded_at=time.monotonic())

    # -- identity -------------------------------------------------------

    def note_node(self, node_id_hex: str, object_addr) -> None:
        """Teach the store a node's object-server address (registration
        time) so pull records' holder addresses resolve to node ids."""
        addr = _addr_str(object_addr)
        if addr and node_id_hex:
            with self._lock:
                self._addr_to_node[addr] = node_id_hex

    def _resolve(self, addr: str) -> str:
        return self._addr_to_node.get(addr, addr)

    # -- ingest ---------------------------------------------------------

    def ingest(self, node_id: str, batch: dict) -> None:
        """Merge one ``flow_batch`` (origin ``node_id`` is the emitting
        process's node — the dst of its pulls, the src of its serves)."""
        records = batch.get("records") or []
        if not records:
            return
        now = time.monotonic()
        node = node_id or ""
        with self._lock:
            self.batches += 1
            for rec in records:
                if not isinstance(rec, dict):
                    continue
                self.records += 1
                nbytes = int(rec.get("bytes") or 0)
                ok = rec.get("outcome") != "error"
                if rec.get("direction") == "out":
                    if nbytes and ok:
                        self._egress[node] = \
                            self._egress.get(node, 0) + nbytes
                    continue
                if nbytes and ok:
                    self._ingress[node] = \
                        self._ingress.get(node, 0) + nbytes
                # Fan-out is tracked BEFORE the link-cap gate: a hot
                # object stays visible even when its cells were
                # squeezed out of a full matrix.
                key = str(rec.get("key") or "")
                if key and ok:
                    self._touch_object(key, node, nbytes, now)
                src = self._resolve(str(rec.get("src") or "")) \
                    or "unknown"
                link = self._link_for(src, node)
                if link is None:
                    continue
                link.last_seen = now
                link.records_total += 1
                link.chunks_total += max(1, int(rec.get("chunks") or 1))
                link.failovers_total += int(rec.get("failovers") or 0)
                if not ok:
                    link.errors_total += 1
                else:
                    link.bytes_total += nbytes
                    link.samples.append(
                        (now, nbytes, float(rec.get("duration") or 0.0)))
                link.trim(now, self.window_s)

    def _link_for(self, src: str, dst: str) -> Optional[_Link]:
        lk = (src, dst)
        link = self._links.get(lk)
        if link is None:
            if len(self._links) >= self.max_links:
                self.dropped_links += 1
                return None
            link = self._links[lk] = _Link()
        self._links.move_to_end(lk)
        return link

    def _touch_object(self, key: str, node: str, nbytes: int,
                      now: float) -> None:
        obj = self._objects.get(key)
        if obj is None:
            while len(self._objects) >= self.max_objects:
                self._objects.popitem(last=False)  # LRU
                self.dropped_objects += 1
            obj = self._objects[key] = _ObjectFanout()
        self._objects.move_to_end(key)
        obj.nodes[node] = now
        obj.bytes_total += nbytes
        obj.pulls += 1
        obj.last_seen = now

    # -- membership / bounds --------------------------------------------

    def mark_node_dead(self, node_id: str) -> None:
        """Start the staleness clock for every link touching the node
        (same contract as ProfileStore/TimeSeriesStore: agents restamp
        live state, dead state ages out)."""
        now = time.monotonic()
        with self._lock:
            for (src, dst), link in self._links.items():
                if node_id in (src, dst) and link.dead_at is None:
                    link.dead_at = now
            stale = [a for a, n in self._addr_to_node.items()
                     if n == node_id]
            for a in stale:
                del self._addr_to_node[a]

    def evict_stale(self) -> None:
        now = time.monotonic()
        idle_horizon = max(4 * self.window_s, 300.0)
        with self._lock:
            doomed = [k for k, l in self._links.items()
                      if (l.dead_at is not None
                          and now - l.dead_at > self.staleness)
                      or now - l.last_seen > idle_horizon]
            for k in doomed:
                del self._links[k]
            gone = [k for k, o in self._objects.items()
                    if now - o.last_seen > idle_horizon]
            for k in gone:
                del self._objects[k]

    # -- series synthesis ----------------------------------------------

    def maybe_publish(self, ts) -> None:
        """Throttled restamp on the passive update cadence — keeps the
        link/fanout gauges decaying toward zero while traffic is idle,
        which is what lets ``slow_link``/``hot_object_fanout`` resolve."""
        now = time.monotonic()
        if now - self._last_publish < self.PUBLISH_MIN_INTERVAL_S:
            return
        self.publish_series(ts)

    def publish_series(self, ts) -> None:
        """Synthesize the link/fan-out series into the head
        TimeSeriesStore (origin component="flow"). Counters are
        cumulative store totals; gauges are windowed and restamped
        EVERY publish (idle => 0) so alert groups go quiet by value,
        not by series eviction."""
        now = time.monotonic()
        self._last_publish = now
        with self._lock:
            bytes_series: Dict[tuple, float] = {}
            chunk_series: Dict[tuple, float] = {}
            failover_series: Dict[tuple, float] = {}
            mbps_series: Dict[tuple, float] = {}
            stalled_series: Dict[tuple, float] = {}
            live_links: set = set()
            for (src, dst), link in self._links.items():
                skey = (src, dst)
                bytes_series[skey] = float(link.bytes_total)
                chunk_series[skey] = float(link.chunks_total)
                failover_series[skey] = float(link.failovers_total)
                wbytes, mbps = link.windowed(now, self.window_s)
                lkey = (f"{src}->{dst}",)
                live_links.add(lkey)
                mbps_series[lkey] = mbps
                stalled_series[lkey] = float(
                    wbytes > 0 and mbps < self.slow_link_mbps)
            fanout_series: Dict[tuple, float] = {}
            live_keys: set = set()
            for key, obj in self._objects.items():
                kkey = (key,)
                live_keys.add(kkey)
                fanout_series[kkey] = float(
                    obj.fanout(now, self.window_s))
            # One final 0 for label sets that fell out of the store so
            # their alert groups read idle, then stop stamping them.
            for lkey in self._published_links - live_links:
                mbps_series[lkey] = 0.0
                stalled_series[lkey] = 0.0
            for kkey in self._published_keys - live_keys:
                fanout_series[kkey] = 0.0
            self._published_links = live_links
            self._published_keys = live_keys
        entries = [
            {"name": "ray_tpu_transfer_link_bytes_total",
             "type": "counter", "tag_keys": ("src", "dst"),
             "series": bytes_series},
            {"name": "ray_tpu_transfer_link_chunks_total",
             "type": "counter", "tag_keys": ("src", "dst"),
             "series": chunk_series},
            {"name": "ray_tpu_transfer_link_failovers_total",
             "type": "counter", "tag_keys": ("src", "dst"),
             "series": failover_series},
            {"name": "ray_tpu_transfer_link_mbps", "type": "gauge",
             "tag_keys": ("link",), "series": mbps_series},
            {"name": "ray_tpu_transfer_link_stalled", "type": "gauge",
             "tag_keys": ("link",), "series": stalled_series},
            {"name": "ray_tpu_object_fanout_nodes", "type": "gauge",
             "tag_keys": ("key",), "series": fanout_series},
        ]
        entries = [e for e in entries if e["series"]]
        if entries:
            ts.ingest_batch("", 0, "flow", entries, now=now)

    # -- reads ----------------------------------------------------------

    def snapshot(self, window: Optional[float] = None) -> dict:
        """The `/api/flows` / `ray-tpu xfer` document: link matrix rows
        (MB/s windowed), fan-out rows, per-node egress/ingress, store
        stats."""
        now = time.monotonic()
        w = self.window_s if window is None else max(1.0, float(window))
        with self._lock:
            links = []
            for (src, dst), link in self._links.items():
                wbytes, mbps = link.windowed(now, min(w, self.window_s))
                links.append({
                    "src": src, "dst": dst,
                    "mbps": mbps,
                    "window_bytes": wbytes,
                    "bytes_total": link.bytes_total,
                    "records": link.records_total,
                    "chunks": link.chunks_total,
                    "failovers": link.failovers_total,
                    "errors": link.errors_total,
                    "p95_s": link.p95_s(),
                    "age_s": max(0.0, now - link.last_seen),
                })
            objects = []
            for key, obj in self._objects.items():
                objects.append({
                    "key": key,
                    "fanout": obj.fanout(now, min(w, self.window_s)),
                    "nodes": sorted(obj.nodes),
                    "bytes_total": obj.bytes_total,
                    "pulls": obj.pulls,
                    "age_s": max(0.0, now - obj.last_seen),
                })
            broadcast = None
            if self._last_broadcast is not None:
                broadcast = dict(self._last_broadcast)
                broadcast["age_s"] = max(
                    0.0, now - broadcast.pop("recorded_at", now))
            out = {
                "window_s": min(w, self.window_s),
                "broadcast": broadcast,
                "links": sorted(links, key=lambda r: -r["mbps"]),
                "objects": sorted(objects,
                                  key=lambda r: (-r["fanout"],
                                                 -r["bytes_total"])),
                "egress": dict(self._egress),
                "ingress": dict(self._ingress),
                "stats": {
                    "links": len(self._links),
                    "objects": len(self._objects),
                    "dropped_links": self.dropped_links,
                    "dropped_objects": self.dropped_objects,
                    "batches": self.batches,
                    "records": self.records,
                },
            }
        return out

    def summary_line(self) -> dict:
        """The compact `ray-tpu top` transfer line: total windowed MB/s,
        active link count, hottest link, max fan-out."""
        snap = self.snapshot()
        active = [r for r in snap["links"] if r["window_bytes"] > 0]
        top = active[0] if active else None
        hot = snap["objects"][0] if snap["objects"] else None
        return {
            "mbps_total": sum(r["mbps"] for r in active),
            "links_active": len(active),
            "top_link": (None if top is None else {
                "src": top["src"], "dst": top["dst"],
                "mbps": top["mbps"]}),
            "max_fanout": (None if hot is None or hot["fanout"] < 2
                           else {"key": hot["key"],
                                 "fanout": hot["fanout"]}),
        }

    def stats(self) -> dict:
        with self._lock:
            return {
                "links": len(self._links),
                "objects": len(self._objects),
                "dropped_links": self.dropped_links,
                "dropped_objects": self.dropped_objects,
                "batches": self.batches,
                "records": self.records,
                "window_s": self.window_s,
                "slow_link_mbps": self.slow_link_mbps,
            }
