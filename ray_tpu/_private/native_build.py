"""Shared build/load machinery for the native (C++) runtime components.

Each component lives in src/ray_tpu_native/<name>.cc and is compiled on
demand into build/lib<name>-<srchash>-<machine>.so. Artifacts are keyed by
source hash + machine so a stale or cross-platform binary is never preferred
over a rebuild (checkout mtimes are meaningless), mirroring how the
reference pins its bazel outputs to the source tree state.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import threading
from typing import Dict, List, Optional

_SRC = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "src", "ray_tpu_native")
# <repo>/build — NOT <repo>/src/build (dirname(_SRC) is <repo>/src).
_BUILD_DIR = os.path.abspath(
    os.path.join(os.path.dirname(_SRC), os.pardir, "build"))

_locks: Dict[str, threading.Lock] = {}
_locks_guard = threading.Lock()


def _lock_for(name: str) -> threading.Lock:
    with _locks_guard:
        return _locks.setdefault(name, threading.Lock())


def cleanup_artifacts(build_dir: str, prefix: str, keep: Optional[str],
                      tmp: Optional[str]) -> None:
    """Remove a failed compile's temp file and superseded hash-named .so
    files so build/ doesn't grow without bound across source edits."""
    try:
        if tmp and os.path.exists(tmp):
            os.unlink(tmp)
        if keep is not None:
            for fname in os.listdir(build_dir):
                if (fname.startswith(prefix) and fname.endswith(".so")
                        and fname != keep):
                    os.unlink(os.path.join(build_dir, fname))
    except OSError:
        pass


def build_library(name: str, extra_flags: Optional[List[str]] = None
                  ) -> Optional[str]:
    """Compile src/ray_tpu_native/<name>.cc into a shared library and return
    its path (cached by source hash + machine). None if unbuildable."""
    src = os.path.join(_SRC, f"{name}.cc")
    if not os.path.exists(src):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    prefix = f"lib{name}-"
    out = os.path.join(
        _BUILD_DIR, f"{prefix}{digest}-{platform.machine()}.so")
    with _lock_for(name):
        if os.path.exists(out):
            return out
        tmp = f"{out}.tmp{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", "-o", tmp,
                 src] + (extra_flags or []),
                check=True, capture_output=True, timeout=120)
            os.replace(tmp, out)
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            cleanup_artifacts(_BUILD_DIR, prefix, keep=None, tmp=tmp)
            return None
        cleanup_artifacts(_BUILD_DIR, prefix, keep=os.path.basename(out),
                          tmp=None)
    return out


#: Every native component linked into the sanitizer stress binary.
STRESS_COMPONENTS = ("sched", "refcount", "pubsub", "shm_store",
                     "config", "memmon")


def build_stress_binary(sanitize: str) -> Optional[str]:
    """Compile the multithreaded stress driver (stress.cc) plus every
    native component into one executable under ``-fsanitize=<sanitize>``
    (thread | address) — the analog of the reference's TSAN/ASAN bazel
    configs (.bazelrc:92-116). Cached by the combined source hash; None
    when g++ or the sanitizer runtime is unavailable."""
    assert sanitize in ("thread", "address"), sanitize
    srcs = [os.path.join(_SRC, "stress.cc")] + [
        os.path.join(_SRC, f"{c}.cc") for c in STRESS_COMPONENTS]
    if not all(os.path.exists(s) for s in srcs):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    digest = h.hexdigest()[:12]
    prefix = f"stress-{sanitize}-"
    out = os.path.join(
        _BUILD_DIR, f"{prefix}{digest}-{platform.machine()}")
    with _lock_for(f"stress:{sanitize}"):
        if os.path.exists(out):
            return out
        tmp = f"{out}.tmp{os.getpid()}"
        try:
            subprocess.run(
                ["g++", "-O1", "-g", "-std=c++17",
                 f"-fsanitize={sanitize}", "-o", tmp] + srcs +
                ["-lpthread", "-lrt"],
                check=True, capture_output=True, timeout=300)
            os.replace(tmp, out)
        except (subprocess.SubprocessError, FileNotFoundError, OSError):
            cleanup_artifacts(_BUILD_DIR, prefix, keep=None, tmp=tmp)
            return None
        cleanup_artifacts(_BUILD_DIR, prefix,
                          keep=os.path.basename(out), tmp=None)
    return out


def load_library(name: str, extra_flags: Optional[List[str]] = None,
                 keep_gil: bool = False) -> Optional[ctypes.CDLL]:
    path = build_library(name, extra_flags)
    if path is None:
        return None
    try:
        # keep_gil (ctypes.PyDLL): microsecond-scale native calls (map
        # insert under an uncontended mutex) must NOT release the GIL —
        # a release/reacquire pair per call becomes a GIL handoff convoy
        # under thread churn (profiled: 1.7us/call quiet, ~80us under an
        # 8-worker task storm). ONLY safe for functions that never block:
        # anything that waits (pubsub long-poll) or moves big payloads
        # (shm memcpy) stays on CDLL.
        return ctypes.PyDLL(path) if keep_gil else ctypes.CDLL(path)
    except OSError:
        return None


_loaded: Dict[str, Optional[ctypes.CDLL]] = {}


def load_library_cached(name: str,
                        extra_flags: Optional[List[str]] = None,
                        configure=None,
                        keep_gil: bool = False) -> Optional[ctypes.CDLL]:
    """Memoized load (failure included). ``configure(lib)`` runs once per
    process to set the ctypes argtypes/restypes — every native component
    wrapper shares this caching pattern instead of re-implementing it."""
    with _lock_for(f"load:{name}"):
        if name not in _loaded:
            lib = load_library(name, extra_flags, keep_gil=keep_gil)
            if lib is not None and configure is not None:
                configure(lib)
            _loaded[name] = lib
        return _loaded[name]
