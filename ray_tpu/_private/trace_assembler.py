"""Head-side trace assembly: per-origin spans -> complete traces.

Every process's finished spans ride ``metrics_batch`` frames to the head
(_private/metrics_agent.py); :class:`ClusterMetrics.update` stamps each
with its origin (node_id, pid, component) and feeds it here. The
assembler groups spans by trace_id into bounded-retention traces
(``RAY_TPU_TRACE_RETENTION`` newest traces; oldest evicted), attributes
every span to a pipeline stage (submit/queue/lease/pull/execute/store/
serve_dispatch/serve_handle), and serves three read surfaces:

* ``list_traces()`` / ``get_trace(id)`` — the ``/api/traces`` dashboard
  routes and ``ray-tpu trace``: full span trees with per-stage breakdown.
* ``summary()`` — cluster-level critical-path attribution: where does
  request time go, by stage (count / total / share / p50 / p95). Also
  exported continuously as the ``ray_tpu_trace_stage_seconds`` histogram.
* ``perfetto()`` / ``flow_events()`` — Chrome-trace JSON with ``s``/``f``
  flow events linking parent→child spans across process boundaries, so
  daemon-hop causality renders as arrows in Perfetto / chrome://tracing.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

DEFAULT_RETENTION = 1000

#: Canonical span-name prefix -> pipeline stage (the glossary in the
#: README's tracing section). Spans may also carry an explicit
#: ``attributes["stage"]``, which wins.
_STAGE_BY_PREFIX = (
    ("driver::submit", "submit"),
    ("sched::queue_wait", "queue"),
    ("sched::lease", "lease"),
    ("data::pull", "pull"),
    ("task::store_result", "store"),
    ("serve::router_dispatch", "serve_dispatch"),
    ("serve::replica_handler", "serve_handle"),
    ("task::", "execute"),
    ("actor_task::", "execute"),
)


def trace_retention() -> int:
    """Retained trace count: ``RAY_TPU_TRACE_RETENTION`` env /
    ``trace_retention`` config flag (default 1000)."""
    raw = os.environ.get("RAY_TPU_TRACE_RETENTION")
    if raw is not None:
        try:
            return max(1, int(float(raw)))
        except ValueError:
            pass
    try:
        from ray_tpu._private.ray_config import runtime_config_value
        return max(1, int(runtime_config_value("trace_retention",
                                               DEFAULT_RETENTION)))
    except Exception:  # noqa: BLE001 - config table unavailable
        return DEFAULT_RETENTION


def span_stage(span: Dict[str, Any]) -> str:
    attrs = span.get("attributes") or {}
    stage = attrs.get("stage")
    if stage:
        return str(stage)
    name = span.get("name", "")
    for prefix, stage in _STAGE_BY_PREFIX:
        if name.startswith(prefix):
            return stage
    return "other"


def _span_duration(span: Dict[str, Any]) -> float:
    dur = span.get("duration")
    if dur is None:
        # Pre-monotonic peers: fall back to the wall-clock pair.
        end = span.get("end_time")
        start = span.get("start_time", 0.0)
        dur = (end - start) if end is not None else 0.0
    return max(0.0, float(dur))


def _origin_label(span: Dict[str, Any]) -> str:
    """The Chrome-trace pid label; matches ClusterMetrics.chrome_spans so
    flow events land on the same tracks as the complete events."""
    return (f"node:{(span.get('node_id') or 'head')[:12]}"
            f"/{span.get('component', '')}-{span.get('pid', 0)}")


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def _stage_breakdown(spans: List[Dict[str, Any]]) -> Dict[str, Any]:
    totals: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for s in spans:
        stage = span_stage(s)
        totals[stage] = totals.get(stage, 0.0) + _span_duration(s)
        counts[stage] = counts.get(stage, 0) + 1
    grand = sum(totals.values()) or 1.0
    return {stage: {"count": counts[stage],
                    "total_s": round(totals[stage], 6),
                    "share": round(totals[stage] / grand, 4)}
            for stage in sorted(totals)}


class TraceAssembler:
    """Bounded trace_id -> spans registry with stage attribution."""

    def __init__(self, retention: Optional[int] = None):
        self._lock = threading.Lock()
        # Insertion-ordered: oldest trace evicted first once over
        # retention. Values are span-dict lists in arrival order.
        self._traces: "OrderedDict[str, List[Dict[str, Any]]]" = \
            OrderedDict()
        self._retention = retention
        self._histogram = None

    @property
    def retention(self) -> int:
        if self._retention is None:
            self._retention = trace_retention()
        return self._retention

    def _observe_stage(self, stage: str, duration: float) -> None:
        if self._histogram is None:
            try:
                from ray_tpu._private import builtin_metrics
                self._histogram = builtin_metrics.trace_stage_seconds()
            except Exception:  # noqa: BLE001 - metrics must not break ingest
                self._histogram = False
        if self._histogram:
            self._histogram.observe(duration, {"stage": stage})

    def add_span(self, span: Dict[str, Any]) -> None:
        """Ingest one origin-stamped span dict (from a metrics batch)."""
        trace_id = span.get("trace_id")
        if not trace_id:
            return
        self._observe_stage(span_stage(span), _span_duration(span))
        with self._lock:
            spans = self._traces.get(trace_id)
            if spans is None:
                spans = self._traces[trace_id] = []
                while len(self._traces) > self.retention:
                    self._traces.popitem(last=False)
            spans.append(dict(span))

    def __len__(self) -> int:
        with self._lock:
            return len(self._traces)

    def _snapshot(self, trace_id: Optional[str] = None
                  ) -> "OrderedDict[str, List[Dict[str, Any]]]":
        with self._lock:
            if trace_id is not None:
                spans = self._traces.get(trace_id)
                return OrderedDict(
                    [(trace_id, list(spans))] if spans else [])
            return OrderedDict((tid, list(sp))
                               for tid, sp in self._traces.items())

    def list_traces(self, limit: Optional[int] = None
                    ) -> List[Dict[str, Any]]:
        """Newest-first trace summaries for ``GET /api/traces``."""
        traces = self._snapshot()
        out = []
        for trace_id in reversed(traces):
            spans = traces[trace_id]
            starts = [s.get("start_time", 0.0) for s in spans]
            ends = [s.get("end_time") or s.get("start_time", 0.0)
                    for s in spans]
            roots = [s for s in spans if not s.get("parent_id")]
            root = min(roots or spans,
                       key=lambda s: s.get("start_time", 0.0))
            out.append({
                "trace_id": trace_id,
                "root": root.get("name", ""),
                "span_count": len(spans),
                "start_time": min(starts) if starts else 0.0,
                "duration_s": round(max(ends) - min(starts), 6)
                              if starts else 0.0,
                "origins": sorted({_origin_label(s) for s in spans}),
            })
            if limit is not None and len(out) >= limit:
                break
        return out

    def get_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """One assembled trace: spans sorted by wall anchor, the
        per-stage critical-path breakdown, and cross-process count."""
        traces = self._snapshot(trace_id)
        spans = traces.get(trace_id)
        if not spans:
            return None
        spans = sorted(spans, key=lambda s: s.get("start_time", 0.0))
        starts = [s.get("start_time", 0.0) for s in spans]
        ends = [s.get("end_time") or s.get("start_time", 0.0)
                for s in spans]
        return {
            "trace_id": trace_id,
            "span_count": len(spans),
            "start_time": min(starts),
            "duration_s": round(max(ends) - min(starts), 6),
            "origins": sorted({_origin_label(s) for s in spans}),
            "stages": _stage_breakdown(spans),
            "spans": spans,
        }

    def summary(self) -> Dict[str, Any]:
        """Cluster-level critical-path attribution across every retained
        trace: per-stage count / total seconds / share / p50 / p95."""
        traces = self._snapshot()
        durations: Dict[str, List[float]] = {}
        transfer_s = 0.0
        transfer_bytes = 0
        transfer_pulls = 0
        for spans in traces.values():
            for s in spans:
                durations.setdefault(span_stage(s), []).append(
                    _span_duration(s))
                # data::pull spans carry the flow plane's enrichment
                # (bytes/chunks/failovers) — roll them up so the
                # summary answers "how much of the critical path is
                # object transfer, and how many bytes was that".
                if s.get("name") == "data::pull":
                    transfer_s += _span_duration(s)
                    transfer_pulls += 1
                    attrs = s.get("attributes") or {}
                    try:
                        transfer_bytes += int(attrs.get("bytes") or 0)
                    except (TypeError, ValueError):
                        pass
        grand = sum(sum(v) for v in durations.values()) or 1.0
        stages = {}
        for stage in sorted(durations):
            vals = sorted(durations[stage])
            total = sum(vals)
            stages[stage] = {
                "count": len(vals),
                "total_s": round(total, 6),
                "share": round(total / grand, 4),
                "p50_s": round(_percentile(vals, 0.50), 6),
                "p95_s": round(_percentile(vals, 0.95), 6),
            }
        return {
            "traces": len(traces),
            "stages": stages,
            "transfer": {
                "pulls": transfer_pulls,
                "total_s": round(transfer_s, 6),
                "share": round(transfer_s / grand, 4),
                "bytes": transfer_bytes,
            },
        }

    def _flow_events_for(self, spans: List[Dict[str, Any]]
                         ) -> List[Dict[str, Any]]:
        by_id = {s.get("span_id"): s for s in spans}
        out = []
        for child in spans:
            parent = by_id.get(child.get("parent_id"))
            if parent is None:
                continue
            if (parent.get("node_id"), parent.get("pid")) == \
                    (child.get("node_id"), child.get("pid")):
                continue  # same process: nesting already shows causality
            # Flow id must be unique per arrow; the child span_id is.
            flow_id = child.get("span_id", "")
            common = {"cat": "trace_flow", "name": "trace",
                      "id": flow_id}
            out.append(dict(common, ph="s",
                            pid=_origin_label(parent),
                            tid=parent.get("span_id", ""),
                            ts=parent.get("start_time", 0.0) * 1e6))
            # bp:"e" binds the finish to the enclosing child slice.
            out.append(dict(common, ph="f", bp="e",
                            pid=_origin_label(child),
                            tid=child.get("span_id", ""),
                            ts=child.get("start_time", 0.0) * 1e6))
        return out

    def flow_events(self) -> List[Dict[str, Any]]:
        """Cross-process flow arrows for every retained trace — merged
        into ``/api/timeline`` next to the complete events."""
        out = []
        for spans in self._snapshot().values():
            out.extend(self._flow_events_for(spans))
        return out

    def perfetto(self, trace_id: Optional[str] = None
                 ) -> List[Dict[str, Any]]:
        """Chrome-trace/Perfetto JSON: complete (``X``) events per span
        plus ``s``/``f`` flow events for every cross-process edge."""
        events = []
        for tid, spans in self._snapshot(trace_id).items():
            for s in spans:
                events.append({
                    "name": s.get("name", ""),
                    "cat": "trace",
                    "ph": "X",
                    "ts": s.get("start_time", 0.0) * 1e6,
                    "dur": _span_duration(s) * 1e6,
                    "pid": _origin_label(s),
                    "tid": s.get("span_id", ""),
                    "args": dict(s.get("attributes") or {},
                                 trace_id=tid,
                                 parent_id=s.get("parent_id"),
                                 stage=span_stage(s)),
                })
            events.extend(self._flow_events_for(spans))
        return events
