"""Binary IDs for tasks/actors/objects/workers/jobs.

TPU-native analog of the reference's ID scheme (reference: src/ray/common/id.h):
ObjectIDs embed the creating TaskID plus a return index so lineage is recoverable
from the ID alone; ActorIDs embed the JobID. IDs are fixed-width random bytes,
hex-printable, hashable, and picklable.
"""

from __future__ import annotations

import os
import threading

_JOB_ID_SIZE = 4
_ACTOR_ID_SIZE = 12  # job(4) + unique(8)
_TASK_ID_SIZE = 16  # actor(12) + unique(4)
_OBJECT_ID_SIZE = 20  # task(16) + index(4)
_WORKER_ID_SIZE = 16
_NODE_ID_SIZE = 16
_PLACEMENT_GROUP_ID_SIZE = 12


class BaseID:
    SIZE = 0
    __slots__ = ("_bytes", "_hash", "_hex")

    def __init__(self, binary: bytes):
        if len(binary) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(binary)}"
            )
        self._bytes = binary
        self._hash = hash((type(self).__name__, binary))

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        # Memoized: ids are immutable and hex() runs ~10x per task on
        # the submit/event hot paths (wire frames, event records, logs).
        try:
            return self._hex
        except AttributeError:
            h = self._hex = self._bytes.hex()
            return h

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = _JOB_ID_SIZE

    _counter = 0
    _lock = threading.Lock()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(value.to_bytes(cls.SIZE, "little"))

    @classmethod
    def next(cls) -> "JobID":
        with cls._lock:
            cls._counter += 1
            return cls.from_int(cls._counter)


class ActorID(BaseID):
    SIZE = _ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + os.urandom(cls.SIZE - JobID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


_task_unique_lock = threading.Lock()
_task_unique_counter = int.from_bytes(os.urandom(4), "little")


def _task_unique() -> bytes:
    """Unique part of a TaskID. Only 4 bytes are available (TaskID layout:
    actor(12) + unique(4)), so randomness would birthday-collide around
    ~2^16 tasks — a long-running driver submits that in minutes. IDs are
    minted by the owning driver process, so a randomly-seeded atomic
    counter is collision-free for 2^32 tasks."""
    global _task_unique_counter
    with _task_unique_lock:
        _task_unique_counter = (_task_unique_counter + 1) & 0xFFFFFFFF
        return _task_unique_counter.to_bytes(4, "little")


class TaskID(BaseID):
    SIZE = _TASK_ID_SIZE

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        actor_part = job_id.binary() + b"\x00" * (ActorID.SIZE - JobID.SIZE)
        return cls(actor_part + _task_unique())

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + _task_unique())

    @classmethod
    def for_actor_creation(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + b"\x00" * (cls.SIZE - ActorID.SIZE))

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[: ActorID.SIZE])


class ObjectID(BaseID):
    SIZE = _OBJECT_ID_SIZE

    @classmethod
    def for_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid colliding with
        # return-object indices.
        return cls(task_id.binary() + (put_index | 0x8000_0000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TaskID.SIZE :], "little") & 0x7FFF_FFFF

    def is_put(self) -> bool:
        return bool(int.from_bytes(self._bytes[TaskID.SIZE :], "little") & 0x8000_0000)


class WorkerID(BaseID):
    SIZE = _WORKER_ID_SIZE


class NodeID(BaseID):
    SIZE = _NODE_ID_SIZE


class PlacementGroupID(BaseID):
    SIZE = _PLACEMENT_GROUP_ID_SIZE
