"""pip/venv runtime-env plugin: per-environment worker interpreters.

Analog of the reference's _private/runtime_env/pip.py + uri_cache.py:
a task/actor with ``runtime_env={"pip": [...]}`` runs in a worker process
whose interpreter lives in a dedicated virtualenv, created once per
unique requirement set (content-hash URI) and reused for the cluster's
lifetime. The venv sees the base environment through
``--system-site-packages`` (jax and friends stay importable without
re-installing) and gets its OWN site-packages ahead of them.

Offline policy (this environment has no network egress): requirements
resolve from a local wheel directory when ``RAY_TPU_PIP_FIND_LINKS`` is
set (``pip install --no-index --find-links ...`` into the venv); without
one, each requirement must already be satisfied by the base environment
(checked against installed distribution metadata) — anything else raises
RuntimeEnvSetupError instead of silently running with missing deps.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional

from ray_tpu.exceptions import RuntimeEnvSetupError

_CACHE_DEFAULT = "/tmp/ray_tpu_venvs"
_lock = threading.Lock()          # guards the dicts below only
_key_locks: Dict[str, threading.Lock] = {}  # per-venv build locks
_ready: Dict[str, str] = {}  # (cache_dir, key) -> python executable


def _dist_name(req: str) -> str:
    return (req.split("==")[0].split(">=")[0].split("<=")[0]
            .split("<")[0].split(">")[0].split("!=")[0].split("~=")[0]
            .split("[")[0].split(";")[0].strip())


def base_satisfies(req: str) -> bool:
    """True iff the BASE environment satisfies this requirement,
    VERSION SPECIFIERS INCLUDED — 'numpy==1.24.0' against an installed
    numpy 2.0 is unsatisfied, not silently accepted. Shared by
    runtime_env.setup's in-process check and the venv resolver."""
    import importlib.metadata as md
    import importlib.util
    try:
        from packaging.requirements import Requirement
        parsed = Requirement(req)
        name, specifier = parsed.name, parsed.specifier
    except Exception:  # noqa: BLE001 - unparseable: fall back to prefix
        name, specifier = _dist_name(req), None
    version = None
    try:
        version = md.version(name)
    except Exception:  # noqa: BLE001 - PackageNotFoundError et al.
        if specifier is None or len(specifier) == 0:
            # Unversioned requirement: a bare importable module (no dist
            # metadata, e.g. a py_modules-style package) still counts.
            return importlib.util.find_spec(
                name.replace("-", "_")) is not None
        return False
    if specifier is None or len(specifier) == 0:
        return True
    return specifier.contains(version, prereleases=True)


def venv_key(pip_list: List[str]) -> str:
    """Content hash of the requirement set + base interpreter: the URI
    under which the materialized venv is cached."""
    payload = json.dumps([sorted(pip_list), sys.executable])
    return hashlib.sha1(payload.encode()).hexdigest()[:16]


def ensure_venv(pip_list: List[str],
                cache_dir: Optional[str] = None) -> str:
    """Create-or-reuse the venv for this requirement set; returns the
    venv's python executable for worker spawning."""
    pip_list = [str(p) for p in (pip_list or [])]
    key = venv_key(pip_list)
    base = cache_dir or os.environ.get("RAY_TPU_VENV_CACHE",
                                       _CACHE_DEFAULT)
    cache_key = f"{os.path.abspath(base)}:{key}"
    # Per-venv build locks: a long first-use pip install must not stall
    # leases of OTHER (especially already-cached) environments.
    with _lock:
        cached = _ready.get(cache_key)
        if cached is not None:
            return cached
        key_lock = _key_locks.setdefault(cache_key, threading.Lock())
    with key_lock:
        with _lock:
            cached = _ready.get(cache_key)
            if cached is not None:
                return cached
        venv_dir = os.path.join(base, key)
        python = os.path.join(venv_dir, "bin", "python")
        if not os.path.exists(python):
            _materialize(venv_dir, python, pip_list)
        with _lock:
            _ready[cache_key] = python
        return python


def _materialize(venv_dir: str, python: str, pip_list: List[str]) -> None:
    import venv as _venv
    find_links = os.environ.get("RAY_TPU_PIP_FIND_LINKS")
    to_install = []
    for req in pip_list:
        if find_links:
            to_install.append(req)
        elif not base_satisfies(req):
            raise RuntimeEnvSetupError(
                f"runtime_env['pip'] requires {req!r}: not installed in "
                "the base environment and no local wheel source is "
                "configured (set RAY_TPU_PIP_FIND_LINKS to a wheel "
                "directory; this cluster has no network egress).")
    tmp = venv_dir + ".tmp"
    if os.path.exists(tmp):
        import shutil
        shutil.rmtree(tmp)
    # system_site_packages: the heavy base stack (jax et al.) stays
    # visible; with_pip=False keeps creation fast — installs go through
    # the BASE interpreter's pip with --target into the venv.
    _venv.EnvBuilder(system_site_packages=True, with_pip=False,
                     symlinks=True).create(tmp)
    ver = f"python{sys.version_info.major}.{sys.version_info.minor}"
    site_dir = os.path.join(tmp, "lib", ver, "site-packages")
    if sys.prefix != sys.base_prefix:
        # The BASE interpreter is itself a virtualenv: EnvBuilder chains
        # to the real python's system site-packages, skipping the base
        # venv's. A .pth file restores visibility of the running env's
        # site-packages (where the heavy stack actually lives).
        import site as _site
        paths = [p for p in _site.getsitepackages() if os.path.isdir(p)]
        # addsitedir (not a bare path line): the base env's OWN .pth
        # files — editable installs live there — must be processed too.
        lines = [f"import site; site.addsitedir({p!r})" for p in paths]
        with open(os.path.join(site_dir, "ray_tpu_base_env.pth"),
                  "w") as f:
            f.write("\n".join(lines) + "\n")
    if to_install:
        cmd = [sys.executable, "-m", "pip", "install", "--quiet",
               "--no-index", "--find-links", find_links,
               "--target", site_dir, *to_install]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
            raise RuntimeEnvSetupError(
                f"pip install into the runtime venv failed "
                f"({' '.join(to_install)}): {proc.stderr[-2000:]}")
    # Atomic publish: concurrent creators race benignly — first rename
    # wins, the loser's tree is discarded.
    try:
        os.makedirs(os.path.dirname(venv_dir), exist_ok=True)
        os.rename(tmp, venv_dir)
    except OSError:
        import shutil
        if os.path.exists(python):
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise


def python_for_env(runtime_env: Optional[dict]) -> Optional[str]:
    """The interpreter a worker for this env must run under, or None for
    the base interpreter. Dispatches across the interpreter-selecting
    plugins: conda (runtime_env_conda) and pip/venv (this module);
    validate() rejects specs naming both."""
    env = runtime_env or {}
    conda_spec = env.get("conda")
    if conda_spec:
        from ray_tpu._private.runtime_env_conda import conda_python
        return conda_python(conda_spec)
    pip_list = env.get("pip")
    if not pip_list:
        return None
    return ensure_venv(list(pip_list))
