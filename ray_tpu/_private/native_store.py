"""Python binding for the native shared-memory object store.

ctypes wrapper over src/ray_tpu_native/shm_store.cc (the plasma analog —
reference: src/ray/object_manager/plasma/client.cc). Large numpy arrays are
written once into the shm arena and read back as ZERO-COPY numpy views over
the mapping; `jax.device_put` on such a view is the host→TPU transfer with
no intermediate host copy.

The library builds on demand with g++ (no pip deps); if no compiler is
available the caller falls back to the pure-Python store.
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
import uuid
from typing import Optional, Tuple

import numpy as np

_lib = None
_lib_lock = threading.Lock()


def _build_library() -> Optional[str]:
    from ray_tpu._private.native_build import build_library
    return build_library("shm_store", extra_flags=["-lrt"])


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _build_library()
        if path is None:
            return None
        lib = ctypes.CDLL(path)
        lib.shm_store_open.restype = ctypes.c_void_p
        lib.shm_store_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                       ctypes.c_int]
        lib.shm_store_close.argtypes = [ctypes.c_void_p]
        lib.shm_store_unlink.argtypes = [ctypes.c_void_p]
        lib.shm_store_create.restype = ctypes.c_int64
        lib.shm_store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                         ctypes.c_uint64]
        lib.shm_store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_get.restype = ctypes.c_int64
        lib.shm_store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.POINTER(ctypes.c_uint64)]
        lib.shm_store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.shm_store_used_bytes.restype = ctypes.c_uint64
        lib.shm_store_used_bytes.argtypes = [ctypes.c_void_p]
        lib.shm_store_num_objects.restype = ctypes.c_uint64
        lib.shm_store_num_objects.argtypes = [ctypes.c_void_p]
        lib.shm_store_write.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_char_p, ctypes.c_uint64]
        lib.shm_store_set_evict_disabled.argtypes = [ctypes.c_void_p,
                                                     ctypes.c_int]
        lib.shm_store_lru_victims.restype = ctypes.c_uint64
        lib.shm_store_lru_victims.argtypes = [ctypes.c_void_p,
                                              ctypes.c_char_p,
                                              ctypes.c_uint64]
        _lib = lib
        return _lib


def native_store_available() -> bool:
    return _load() is not None


def reap_stale_arenas() -> int:
    """Unlink /dev/shm arenas whose creating process is dead (the pid
    is embedded in the name). SIGKILLed daemons/heads cannot unlink
    their own mappings; without this housekeeping every crashed run
    leaks its whole arena — measured 118GB of resident shm after one
    day of test/bench churn, silently starving later runs. Mirrors
    _reap_stale_spill_dirs (reference: the raylet reclaims its
    predecessor's store on restart). Returns bytes freed."""
    import re

    from ray_tpu._private import procinfo
    freed = 0
    try:
        entries = os.listdir("/dev/shm")
    except OSError:
        return 0
    for fname in entries:
        m = re.match(r"ray_tpu_(\d+)_", fname)
        if m is None:
            continue
        pid = int(m.group(1))
        if pid == os.getpid() or procinfo.pid_alive(pid):
            continue
        path = os.path.join("/dev/shm", fname)
        try:
            freed += os.path.getsize(path)
            os.unlink(path)
        except OSError:
            continue
    return freed


class NativeObjectStore:
    """One shm arena. put/get numpy arrays (zero-copy reads) or raw bytes."""

    def __init__(self, capacity: int = 1 << 30, name: Optional[str] = None,
                 create: bool = True):
        lib = _load()
        if lib is None:
            raise RuntimeError("native store library unavailable")
        self._lib = lib
        self.name = name or f"/ray_tpu_{os.getpid()}_{uuid.uuid4().hex[:8]}"
        self.capacity = capacity
        self._handle = lib.shm_store_open(self.name.encode(), capacity,
                                          1 if create else 0)
        if not self._handle:
            raise RuntimeError(f"failed to open shm store {self.name}")
        # Map the arena read-only in Python for zero-copy views. When
        # attaching, the real size comes from the file (the creator chose
        # the capacity).
        fd = os.open(f"/dev/shm{self.name}", os.O_RDONLY)
        try:
            real_size = os.fstat(fd).st_size
            self.capacity = real_size
            self._map = mmap.mmap(fd, real_size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        self._wmap = None  # lazy write mapping (writable_view)
        self._closed = False

    # -- raw bytes -------------------------------------------------------

    def put_bytes(self, object_id: str, payload: bytes) -> bool:
        oid = object_id.encode()
        off = self._lib.shm_store_create(self._handle, oid, len(payload))
        if off == -2:
            return True  # already stored (idempotent puts)
        if off < 0:
            return False
        self._lib.shm_store_write(self._handle, off, payload, len(payload))
        self._lib.shm_store_seal(self._handle, oid)
        return True

    def get_bytes(self, object_id: str) -> Optional[memoryview]:
        """Zero-copy view; caller must release(object_id) when done."""
        size = ctypes.c_uint64()
        off = self._lib.shm_store_get(self._handle, object_id.encode(),
                                      ctypes.byref(size))
        if off < 0:
            return None
        return memoryview(self._map)[off:off + size.value]

    # -- chunked writes (node-to-node pulls stream straight into shm) ----

    def put_parts(self, object_id: str, parts, size: Optional[int] = None
                  ) -> bool:
        """Lay a sequence of bytes-like parts down contiguously as one
        sealed object — the OOB serialization path: header + raw array
        buffers land with one memcpy each, never joined into an
        intermediate full-payload bytes object."""
        if size is None:
            size = sum(len(p) for p in parts)
        off = self._lib.shm_store_create(self._handle, object_id.encode(),
                                         size)
        if off == -2:
            return True  # already stored (idempotent puts)
        if off < 0:
            return False
        wview = self.writable_view(off, size)
        try:
            pos = 0
            if wview is not None:
                for p in parts:
                    n = len(p)
                    wview[pos:pos + n] = p
                    pos += n
            else:
                for p in parts:
                    chunk = bytes(p)
                    self._lib.shm_store_write(self._handle, off + pos,
                                              chunk, len(chunk))
                    pos += len(chunk)
        except BaseException:
            self.abort(object_id)
            raise
        finally:
            if wview is not None:
                try:
                    wview.release()
                except BufferError:
                    pass
        self._lib.shm_store_seal(self._handle, object_id.encode())
        return True

    #: create() result when the key is already stored. Distinct from
    #: None (no room): a duplicate put is an idempotent no-op, while a
    #: full arena means the caller should spill and retry.
    DUPLICATE = "duplicate"

    def create(self, object_id: str, size: int):
        """Reserve an unsealed allocation; returns its arena offset,
        ``NativeObjectStore.DUPLICATE`` when the key already exists
        (idempotent re-put — do NOT write), or None when there is no
        room. Complete with write_at + seal."""
        off = self._lib.shm_store_create(self._handle, object_id.encode(),
                                         size)
        if off == -2:
            return self.DUPLICATE
        if off < 0:
            return None
        return off

    def write_at(self, offset: int, chunk: bytes) -> None:
        self._lib.shm_store_write(self._handle, offset, chunk, len(chunk))

    def writable_view(self, offset: int, size: int):
        """Writable memoryview over an UNSEALED create() allocation, so
        network receives can land straight in shm (recv_into — no
        intermediate bytes object, no second memcpy). None when a
        write mapping cannot be made. Only the creating thread may
        touch the region before seal()."""
        wmap = self._wmap
        if wmap is None:
            try:
                fd = os.open(f"/dev/shm{self.name}", os.O_RDWR)
                try:
                    wmap = mmap.mmap(fd, self.capacity)
                finally:
                    os.close(fd)
                self._wmap = wmap
            except OSError:
                return None
        return memoryview(wmap)[offset:offset + size]

    def seal(self, object_id: str) -> None:
        self._lib.shm_store_seal(self._handle, object_id.encode())

    def abort(self, object_id: str) -> None:
        """Discard an unsealed create() without ever publishing it."""
        self._lib.shm_store_abort(self._handle, object_id.encode())

    # -- numpy arrays ----------------------------------------------------

    def put_array(self, object_id: str, arr: np.ndarray) -> bool:
        """Header (dtype/shape) + raw buffer in one allocation."""
        arr = np.ascontiguousarray(arr)
        header = f"{arr.dtype.str}|{','.join(map(str, arr.shape))}".encode()
        meta = len(header).to_bytes(4, "little") + header
        payload = meta + arr.tobytes()
        return self.put_bytes(object_id, payload)

    def get_array(self, object_id: str) -> Optional[np.ndarray]:
        """Returns a READ-ONLY zero-copy view into shared memory."""
        view = self.get_bytes(object_id)
        if view is None:
            return None
        hlen = int.from_bytes(view[:4], "little")
        # rsplit: dtype.str itself starts with '|' for non-endian types
        # (uint8 is '|u1'), so only the LAST separator splits the fields.
        dtype_str, shape_str = bytes(
            view[4:4 + hlen]).decode().rsplit("|", 1)
        shape = tuple(int(x) for x in shape_str.split(",")) if shape_str \
            else ()
        data = view[4 + hlen:]
        arr = np.frombuffer(data, dtype=np.dtype(dtype_str)).reshape(shape)
        return arr

    # -- lifecycle -------------------------------------------------------

    def contains(self, object_id: str) -> bool:
        return bool(self._lib.shm_store_contains(self._handle,
                                                 object_id.encode()))

    def release(self, object_id: str) -> None:
        self._lib.shm_store_release(self._handle, object_id.encode())

    def delete(self, object_id: str) -> bool:
        return self._lib.shm_store_delete(self._handle,
                                          object_id.encode()) == 0

    def set_evict_disabled(self, disabled: bool) -> None:
        """When disabled, create() fails (-1) under pressure instead of
        LRU-evicting — the owner spills victims to disk itself, so a
        still-needed object can never be silently lost."""
        self._lib.shm_store_set_evict_disabled(self._handle,
                                               1 if disabled else 0)

    def lru_victims(self, max_bytes: int = 1 << 16) -> list:
        """Evictable (sealed, unpinned) object ids in LRU order."""
        buf = ctypes.create_string_buffer(max_bytes)
        n = self._lib.shm_store_lru_victims(self._handle, buf, max_bytes)
        if n == 0:
            return []
        ids = bytes(buf.raw).split(b"\0")
        return [i.decode() for i in ids[:int(n)]]

    def used_bytes(self) -> int:
        return self._lib.shm_store_used_bytes(self._handle)

    def num_objects(self) -> int:
        return self._lib.shm_store_num_objects(self._handle)

    def close(self, unlink: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if unlink:
            self._lib.shm_store_unlink(self._handle)
        self._lib.shm_store_close(self._handle)
        if self._wmap is not None:
            try:
                self._wmap.close()
            except BufferError:
                pass
            self._wmap = None
        try:
            self._map.close()
        except BufferError:
            # Zero-copy views are still alive; the mapping is reclaimed
            # when they are garbage collected (the unlink above already
            # removed the name).
            pass

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass
