"""Deterministic fault injection for transport paths.

Enabled via the ``RAY_TPU_CHAOS`` environment variable (inherited by
daemon subprocesses) or programmatically via :func:`configure`::

    RAY_TPU_CHAOS="send_oserror:p=0.05:seed=7"
    RAY_TPU_CHAOS="sock_close:site=head.send:after=5:times=1;delay_ms:ms=20"

Spec grammar: ops separated by ``;``; each op is ``KIND[:k=v...]``.

Kinds
    send_oserror   raise an OSError from a ``*.send`` site
    recv_oserror   raise an OSError from a ``*.recv`` site
    sock_close     shutdown+close the socket at the site, then raise
    delay_ms       sleep ``ms`` milliseconds at the site
    kill           raise :class:`ChaosKill` from a ``*kill`` site (serve
                   replicas treat it as sudden death: the actor plays
                   dead from then on, exercising failover/replacement)
    io_oserror     raise an OSError from a ``*_error`` storage-IO site
                   (spill writes/restores; degrades a tier instead of
                   failing the caller)
    partition      network partition: blackhole every matching ``.send``
                   / ``.recv`` call by raising :class:`ChaosPartition`
                   (an unreachable peer, NOT a reset — the membership
                   layer classifies it like a probe timeout). Scope
                   with ``site`` (``head`` = everything the head sends
                   or receives on its session/health channels →
                   bidirectional head↔daemon partition; ``daemon`` =
                   the daemon's side; ``pull`` = the daemon↔daemon data
                   plane). ``ms`` (default 0 = no window) arms a heal
                   timer on the first fire: matching calls are
                   blackholed for ``ms`` milliseconds, then the
                   partition heals and never fires again — partition →
                   suspicion → death declaration → heal → fenced
                   re-register, in one deterministic spec.

Params
    p      firing probability per matching call (default 1.0)
    seed   per-op RNG seed — same seed, same call sequence, same fires
    site   substring filter on the injection-site name
    after  skip the first N matching calls
    times  fire at most N times (0 = unlimited)
    ms     sleep duration for delay_ms (default 10); heal-after
           duration for partition (default 0 = never heals)

Sites: ``head.send`` / ``head.recv`` (head side of a session channel),
``daemon.send`` / ``daemon.recv`` (daemon side), ``pull.send``
(dataplane pooled pull sockets), ``head.health.send`` /
``head.health.recv`` (head-side liveness probe), ``daemon.health.send``
/ ``daemon.health.recv`` (daemon health-channel loop),
``daemon.resume.send`` (resume handshake — a partition must also block
the daemon's attempt to re-attach its broken session), ``serve.replica_kill`` /
``serve.replica_delay_ms`` (serve replica request path — evaluated at
the top of every ``handle_request``), ``spill.write_error`` /
``spill.restore_error`` (spill-backend IO, see _private/spill.py),
``train.worker_kill`` / ``train.result_delay_ms`` /
``train.ping_delay_ms`` / ``train.start_delay_ms`` (train-worker gang
RPCs, see train/_internal/worker_group.py — a fired kill makes the
rank play dead so the BackendExecutor's system-failure gang restart is
exercised deterministically), ``train.ckpt_shard_write_error`` /
``train.ckpt_shard_kill`` (per-rank sharded checkpoint writes, see
train/_internal/sharded_checkpoint.py — an injected ``io_oserror``
fails that rank's shard write cleanly so the save attempt aborts
without committing, while a kill takes the rank down mid-save to prove
torn shard sets stay uncommitted and get garbage-collected).

Hot paths guard on the module-level :data:`ACTIVE` flag, so with chaos
disabled the per-frame cost is a single attribute read and no call.
"""

from __future__ import annotations

import logging
import os
import random
import socket
import threading
import time
from typing import List, Optional

logger = logging.getLogger(__name__)

ACTIVE = False

_LOCK = threading.Lock()
_OPS: List["_Op"] = []
_DEFAULT_SEED = 0xC4A05
_KINDS = ("send_oserror", "recv_oserror", "sock_close", "delay_ms", "kill",
          "io_oserror", "partition")


class ChaosError(OSError):
    """Injected transport failure (distinguishable from real ones)."""


class ChaosKill(ChaosError):
    """Injected sudden-death signal (serve replicas catch this and play
    dead — every subsequent call raises ActorDiedError)."""


class ChaosPartition(ChaosError):
    """Injected network partition: the peer is unreachable, not reset.

    Channels treat it like any transient OSError (mark broken, park for
    resume); the membership layer classifies it like a probe TIMEOUT —
    evidence of partition feeding the suspicion score, never the
    immediate process-is-gone death path."""


class _Op:
    __slots__ = ("kind", "p", "site", "after", "times", "ms", "rng",
                 "seen", "fired", "started")

    def __init__(self, kind: str, params: dict):
        self.kind = kind
        self.p = float(params.get("p", 1.0))
        self.site = params.get("site", "")
        self.after = int(params.get("after", 0))
        self.times = int(params.get("times", 0))
        # delay_ms: sleep duration. partition: heal-after window from
        # the first fire (0 = the partition never heals on its own).
        self.ms = float(params.get("ms",
                                   0.0 if kind == "partition" else 10.0))
        self.rng = random.Random(int(params.get("seed", _DEFAULT_SEED)))
        self.seen = 0
        self.fired = 0
        self.started: Optional[float] = None  # partition: first-fire time


def configure(spec: Optional[str]) -> List[_Op]:
    """Parse a chaos spec string, replacing any previous configuration.

    An empty/None spec disables injection entirely.
    """
    global ACTIVE
    ops = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        kind = fields[0].strip()
        if kind not in _KINDS:
            raise ValueError(
                f"unknown chaos op {kind!r} (expected one of {_KINDS})")
        params = {}
        for field in fields[1:]:
            key, _, value = field.partition("=")
            params[key.strip()] = value.strip()
        ops.append(_Op(kind, params))
    with _LOCK:
        _OPS[:] = ops
        ACTIVE = bool(ops)
    return list(ops)


def reset() -> None:
    """Disable injection and drop all configured ops."""
    configure("")


def stats() -> List[dict]:
    """Per-op match/fire counters (for asserting a fault really fired)."""
    with _LOCK:
        return [{"kind": op.kind, "site": op.site, "seen": op.seen,
                 "fired": op.fired} for op in _OPS]


def maybe_inject(site: str, sock=None) -> None:
    """Evaluate the active ops at an injection site.

    May sleep, close ``sock``, or raise :class:`ChaosError`. Callers
    must guard with ``if chaos.ACTIVE:`` to keep disabled-path cost at
    one attribute read.
    """
    fire = None
    with _LOCK:
        for op in _OPS:
            if op.site and op.site not in site:
                continue
            if op.kind == "send_oserror" and ".send" not in site:
                continue
            if op.kind == "recv_oserror" and ".recv" not in site:
                continue
            if op.kind == "kill" and "kill" not in site:
                continue
            if op.kind == "io_oserror" and "_error" not in site:
                continue
            if (op.kind == "partition" and ".send" not in site
                    and ".recv" not in site):
                continue
            op.seen += 1
            if op.seen <= op.after:
                continue
            if op.kind == "partition" and op.started is not None:
                # Window armed on the first fire: every matching call is
                # blackholed until ``ms`` elapses, then the partition
                # heals for good (p/times no longer consulted).
                if (time.monotonic() - op.started) * 1000.0 < op.ms:
                    op.fired += 1
                    fire = op
                    break
                continue
            if op.times and op.fired >= op.times:
                continue
            if op.p < 1.0 and op.rng.random() >= op.p:
                continue
            op.fired += 1
            if op.kind == "partition" and op.ms > 0 and op.started is None:
                op.started = time.monotonic()
            fire = op
            break
    if fire is None:
        return
    if fire.kind == "delay_ms":
        time.sleep(fire.ms / 1000.0)
        return
    if fire.kind == "kill":
        raise ChaosKill(f"chaos[kill] injected at {site}")
    if fire.kind == "partition":
        raise ChaosPartition(f"chaos[partition] injected at {site}")
    if fire.kind == "sock_close" and sock is not None:
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
    raise ChaosError(f"chaos[{fire.kind}] injected at {site}")


_env_spec = os.environ.get("RAY_TPU_CHAOS", "")
if _env_spec:
    try:
        configure(_env_spec)
    except ValueError:
        logger.warning("ignoring malformed RAY_TPU_CHAOS spec %r", _env_spec)
