"""Head-side store for the cluster's continuous profiles.

The per-process :class:`~ray_tpu._private.profiling.ProfilerAgent`
ships folded stacks as ``profile_batch`` frames on the metrics cadence;
this store merges them per origin ``(node_id, pid, component)`` into
bounded WINDOWED buckets (reference: Google-Wide Profiling's
always-on collector; the reference Ray dashboard only has on-demand
py-spy, so saturation incidents there are unattributable after the
fact). It serves three surfaces:

* Merged flamegraphs — :meth:`flame` renders the last ``window``
  seconds across any origin filter as collapsed text or a speedscope
  document, each stack rooted at ``component@node/pid`` so one graph
  shows where the whole cluster burns CPU.
* Window-vs-window diffs — :meth:`diff` subtracts the previous window's
  weights from the current one per stack ("what got hot since then").
* The loop-lag FLIGHT RECORDER — :meth:`observe_loop_lag` watches every
  ingested ``ray_tpu_loop_lag_seconds`` sample; a crossing of
  ``RAY_TPU_PROFILE_FLIGHT_LAG_S`` snapshots the lagging origin's hot
  stacks from the live window into a bounded incident ring
  (``/api/profile/incidents``, ``ray-tpu profile --report``) — the
  gauge spike arrives already annotated with named functions.

Bounds mirror ``timeseries.py``: at most ``profile_max_series``
origins, at most ``profile_max_stacks`` distinct stacks per bucket
(overflow folds into a ``<truncated>`` leaf and is counted), retention
``RAY_TPU_PROFILE_WINDOW_S`` (``<= 0`` disables the store), dead-node
eviction off the membership death push, and a ``profile_max_incidents``
ring. All timestamps are ``time.monotonic()``; reads report ages.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_WINDOW_S = 300.0
DEFAULT_MAX_ORIGINS = 256
DEFAULT_MAX_STACKS = 2000
DEFAULT_FLIGHT_LAG_S = 1.0
DEFAULT_MAX_INCIDENTS = 32
#: Bucket width: the merge granularity for windows and diffs. Fine
#: enough that a 60s diff window sees several buckets, coarse enough
#: that a 300s window is ~10 dicts per origin.
BUCKET_S = 30.0
#: Stacks kept per flight-recorder incident.
INCIDENT_TOP_N = 20
#: Minimum spacing between incidents for the SAME loop gauge — a
#: saturated loop re-crossing the threshold every tick must not flood
#: the ring with near-identical snapshots.
INCIDENT_COOLDOWN_S = 30.0

_TRUNCATED_KEY = "<truncated>"


def _cfg(env: str, flag: str, default: float) -> float:
    raw = os.environ.get(env, "")
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    from ray_tpu._private.ray_config import runtime_config_value
    return float(runtime_config_value(flag, default))


def configured_window_s() -> float:
    return _cfg("RAY_TPU_PROFILE_WINDOW_S", "profile_window_s",
                DEFAULT_WINDOW_S)


def configured_max_origins() -> int:
    return int(_cfg("RAY_TPU_PROFILE_MAX_SERIES", "profile_max_series",
                    DEFAULT_MAX_ORIGINS))


def configured_max_stacks() -> int:
    return int(_cfg("RAY_TPU_PROFILE_MAX_STACKS", "profile_max_stacks",
                    DEFAULT_MAX_STACKS))


def configured_flight_lag_s() -> float:
    """Flight-recorder trigger threshold; ``<= 0`` disables it."""
    return _cfg("RAY_TPU_PROFILE_FLIGHT_LAG_S", "profile_flight_lag_s",
                DEFAULT_FLIGHT_LAG_S)


def configured_max_incidents() -> int:
    return int(_cfg("RAY_TPU_PROFILE_MAX_INCIDENTS",
                    "profile_max_incidents", DEFAULT_MAX_INCIDENTS))


class _OriginProfile:
    """One process's windowed folded-stack buckets."""

    __slots__ = ("buckets", "last_seen", "dead_at", "samples")

    def __init__(self, maxlen: int):
        # deque of [bucket_start_mono, {folded_stack: count}] — append
        # only at the tail; maxlen retires buckets past the window.
        self.buckets: deque = deque(maxlen=maxlen)
        self.last_seen = time.monotonic()
        self.dead_at: Optional[float] = None
        self.samples = 0  # lifetime stack walks merged into this origin


class ProfileStore:
    """Bounded windowed folded-stack buckets per cluster origin."""

    def __init__(self, window_s: Optional[float] = None,
                 max_origins: Optional[int] = None,
                 max_stacks: Optional[int] = None,
                 staleness: Optional[float] = None,
                 bucket_s: float = BUCKET_S):
        self.window_s = (configured_window_s() if window_s is None
                         else float(window_s))
        self.max_origins = (configured_max_origins() if max_origins is None
                            else int(max_origins))
        self.max_stacks = (configured_max_stacks() if max_stacks is None
                           else int(max_stacks))
        self.staleness = (30.0 if staleness is None else float(staleness))
        self.bucket_s = float(bucket_s)
        self.enabled = self.window_s > 0
        self._lock = threading.Lock()
        self._origins: Dict[Tuple[str, int, str], _OriginProfile] = {}
        self.dropped_origins = 0
        self.dropped_stacks = 0
        self._incidents: deque = deque(maxlen=configured_max_incidents())
        self._incident_last: Dict[str, float] = {}  # loop -> mono ts

    # -- ingest ----------------------------------------------------------

    def _buckets_per_origin(self) -> int:
        return max(2, int(self.window_s / self.bucket_s) + 1)

    def ingest(self, node_id: str, pid: int, component: str,
               stacks: Dict[str, int], samples: int = 0,
               now: Optional[float] = None) -> None:
        """Merge one ``profile_batch`` payload into its origin's current
        bucket. Per-bucket distinct-stack count is capped: overflow
        weight folds into ``<truncated>`` (total sample weight stays
        honest even when stack shapes churn without bound)."""
        if not self.enabled or not stacks:
            return
        now = time.monotonic() if now is None else now
        key = (node_id or "", int(pid or 0), component or "")
        with self._lock:
            origin = self._origins.get(key)
            if origin is None:
                if len(self._origins) >= self.max_origins:
                    self.dropped_origins += 1
                    return
                origin = self._origins[key] = _OriginProfile(
                    self._buckets_per_origin())
            origin.last_seen = now
            origin.dead_at = None  # a publishing origin is alive
            bucket_ts = now - (now % self.bucket_s)
            if origin.buckets and origin.buckets[-1][0] >= bucket_ts:
                bucket = origin.buckets[-1][1]
            else:
                bucket = {}
                origin.buckets.append([bucket_ts, bucket])
            for stack, count in stacks.items():
                if stack in bucket or len(bucket) < self.max_stacks:
                    bucket[stack] = bucket.get(stack, 0) + int(count)
                else:
                    bucket[_TRUNCATED_KEY] = \
                        bucket.get(_TRUNCATED_KEY, 0) + int(count)
                    self.dropped_stacks += 1
            walked = int(samples or 0) or sum(
                int(c) for c in stacks.values())
            origin.samples += walked

    # -- membership / bounds ---------------------------------------------

    def mark_node_dead(self, node_id: str) -> None:
        """Start the staleness clock for the node's origins (wired to
        the membership death push, like the time-series store)."""
        now = time.monotonic()
        with self._lock:
            for (nid, _pid, _comp), origin in self._origins.items():
                if nid == node_id and origin.dead_at is None:
                    origin.dead_at = now

    def evict_stale(self) -> None:
        now = time.monotonic()
        with self._lock:
            dead = [key for key, origin in self._origins.items()
                    if (origin.dead_at is not None
                        and now - origin.dead_at > self.staleness)
                    or now - origin.last_seen > self.window_s
                    + self.staleness]
            for key in dead:
                del self._origins[key]

    def origins(self) -> List[Tuple[str, int, str]]:
        with self._lock:
            return list(self._origins)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "origins": len(self._origins),
                "dropped_origins": self.dropped_origins,
                "dropped_stacks": self.dropped_stacks,
                "incidents": len(self._incidents),
                "window_s": self.window_s,
            }

    # -- queries ---------------------------------------------------------

    def merged(self, component: Optional[str] = None,
               node_id: Optional[str] = None,
               start_age_s: Optional[float] = None,
               end_age_s: float = 0.0,
               prefix_origin: bool = True,
               now: Optional[float] = None) -> Dict[str, int]:
        """Folded stacks merged across matching origins over the age
        range ``[end_age_s, start_age_s]`` seconds ago (default: the
        whole retention window). ``prefix_origin`` roots every stack at
        ``component@node8/pid`` so the merged flamegraph keeps cluster
        attribution."""
        now = time.monotonic() if now is None else now
        start_age = self.window_s if start_age_s is None \
            else float(start_age_s)
        oldest = now - start_age
        newest = now - float(end_age_s)
        merged: Dict[str, int] = {}
        with self._lock:
            items = list(self._origins.items())
        for (nid, pid, comp), origin in items:
            if component is not None and comp != component:
                continue
            if node_id is not None and not nid.startswith(node_id):
                continue
            root = f"{comp}@{nid[:8] or 'head'}/{pid}"
            for bucket_ts, stacks in list(origin.buckets):
                # A bucket counts if it overlaps the age range at all.
                if bucket_ts + self.bucket_s <= oldest or \
                        bucket_ts > newest:
                    continue
                for stack, count in stacks.items():
                    key = f"{root};{stack}" if prefix_origin else stack
                    merged[key] = merged.get(key, 0) + count
        return merged

    def flame(self, component: Optional[str] = None,
              node_id: Optional[str] = None,
              window: Optional[float] = None, fmt: str = "folded"):
        """Merged cluster/per-component flamegraph over the last
        ``window`` seconds: collapsed text ('folded'), a speedscope
        document ('speedscope'), or the raw mapping ('dict')."""
        counts = self.merged(component=component, node_id=node_id,
                             start_age_s=window)
        if fmt == "dict":
            return counts
        if fmt == "speedscope":
            from ray_tpu._private.profiling import folded_to_speedscope
            return folded_to_speedscope(counts, name="ray_tpu-cluster")
        if fmt == "folded":
            return "\n".join(f"{k} {v}"
                             for k, v in sorted(counts.items()))
        raise ValueError(f"unknown flame format {fmt!r}")

    def diff(self, window: float = 60.0,
             component: Optional[str] = None,
             node_id: Optional[str] = None,
             limit: int = 50) -> List[Dict[str, Any]]:
        """Window-vs-window stack diff: current ``window`` seconds vs
        the ``window`` before it, sorted by weight delta (descending) —
        "which stacks got hot". Entries carry current/previous counts."""
        w = float(window)
        cur = self.merged(component=component, node_id=node_id,
                          start_age_s=w)
        prev = self.merged(component=component, node_id=node_id,
                           start_age_s=2 * w, end_age_s=w)
        out = []
        for stack in set(cur) | set(prev):
            c, p = cur.get(stack, 0), prev.get(stack, 0)
            if c == p:
                continue
            out.append({"stack": stack, "current": c, "previous": p,
                        "delta": c - p})
        out.sort(key=lambda e: -abs(e["delta"]))
        return out[:max(1, int(limit))]

    def top_stacks(self, component: Optional[str] = None,
                   node_id: Optional[str] = None,
                   window: Optional[float] = None,
                   n: int = INCIDENT_TOP_N) -> List[List[Any]]:
        counts = self.merged(component=component, node_id=node_id,
                             start_age_s=window)
        ranked = sorted(counts.items(), key=lambda kv: -kv[1])
        return [[stack, count] for stack, count in ranked[:n]]

    # -- flight recorder -------------------------------------------------

    def observe_loop_lag(self, loop: str, lag_s: float, node_id: str,
                         pid: int, component: str,
                         now: Optional[float] = None) -> bool:
        """Feed one ``ray_tpu_loop_lag_seconds`` sample; a threshold
        crossing snapshots the lagging origin's hot stacks from the
        current window into the incident ring. Returns True when an
        incident was recorded."""
        if not self.enabled:
            return False
        threshold = configured_flight_lag_s()
        if threshold <= 0 or lag_s < threshold:
            return False
        now = time.monotonic() if now is None else now
        with self._lock:
            last = self._incident_last.get(loop)
            if last is not None and now - last < INCIDENT_COOLDOWN_S:
                return False
            self._incident_last[loop] = now
        # The lagging component's view first (this origin's node +
        # component); empty — e.g. lag arrived before any profile
        # batch — falls back to the whole cluster so the incident is
        # never stackless when ANY profile data exists.
        stacks = self.top_stacks(component=component or None,
                                 node_id=node_id or None)
        scope = "origin"
        if not stacks:
            stacks = self.top_stacks()
            scope = "cluster"
        incident = {
            "loop": loop,
            "lag_s": float(lag_s),
            "threshold_s": threshold,
            "node_id": node_id or "",
            "pid": int(pid or 0),
            "component": component or "",
            "window_s": self.window_s,
            "scope": scope,
            "top_stacks": stacks,
            "recorded_mono": now,
        }
        with self._lock:
            self._incidents.appendleft(incident)
        return True

    def incidents(self) -> List[Dict[str, Any]]:
        """Newest-first incident ring; ``age_s`` replaces the internal
        monotonic stamp so callers never see raw monotonic values."""
        now = time.monotonic()
        with self._lock:
            rows = [dict(rec) for rec in self._incidents]
        for rec in rows:
            rec["age_s"] = max(0.0, now - rec.pop("recorded_mono"))
        return rows
