"""Publisher/subscriber with long-poll semantics — native + Python twin.

The in-process analog of the reference's pubsub layer
(src/ray/pubsub/publisher.h:298 / subscriber.h:329, the PubsubLongPolling
rpc): channels keyed by (channel, key); subscribers long-poll for
messages. Used for object-location / membership style notifications;
ctypes releases the GIL around the native blocking poll so Python worker
threads can park in it cheaply.
"""

from __future__ import annotations

import collections
import ctypes
import os
import threading
from typing import Dict, Optional, Set, Tuple


def _load():
    from ray_tpu._private.native_build import load_library_cached

    def configure(lib):
        P, L, C = ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p
        lib.rpb_create.restype = P
        lib.rpb_destroy.argtypes = [P]
        lib.rpb_subscribe.argtypes = [P, C, C, C]
        lib.rpb_unsubscribe.argtypes = [P, C, C, C]
        lib.rpb_drop_subscriber.argtypes = [P, C]
        lib.rpb_publish.restype = L
        lib.rpb_publish.argtypes = [P, C, C, C]
        lib.rpb_poll.restype = L
        lib.rpb_poll.argtypes = [P, C, L, ctypes.c_char_p, L]
        lib.rpb_inbox_size.restype = L
        lib.rpb_inbox_size.argtypes = [P, C]

    return load_library_cached("pubsub", configure=configure)


def native_pubsub_available() -> bool:
    if os.environ.get("RAY_TPU_NATIVE_PUBSUB", "1") == "0":
        return False
    return _load() is not None


class NativePubsub:
    def __init__(self):
        self._lib = _load()
        self._h = self._lib.rpb_create()

    def __del__(self):
        try:
            self._lib.rpb_destroy(self._h)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def subscribe(self, sub_id: str, channel: str, key: str = "") -> None:
        self._lib.rpb_subscribe(self._h, sub_id.encode(), channel.encode(),
                                key.encode())

    def unsubscribe(self, sub_id: str, channel: str, key: str = "") -> None:
        self._lib.rpb_unsubscribe(self._h, sub_id.encode(),
                                  channel.encode(), key.encode())

    def drop_subscriber(self, sub_id: str) -> None:
        self._lib.rpb_drop_subscriber(self._h, sub_id.encode())

    def publish(self, channel: str, key: str, payload: str) -> int:
        return int(self._lib.rpb_publish(
            self._h, channel.encode(), key.encode(), payload.encode()))

    def poll(self, sub_id: str, timeout: float = 1.0
             ) -> Optional[Tuple[str, str, str]]:
        """Block up to ``timeout`` seconds; returns (channel, key, payload)
        or None on timeout."""
        cap = 4096
        timeout_ms = int(timeout * 1000)
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.rpb_poll(self._h, sub_id.encode(), timeout_ms,
                                   buf, cap)
            if n <= 0:
                return None
            if n < cap:
                channel, key, payload = buf.value.decode().split("|", 2)
                return channel, key, payload
            cap = n + 1
            timeout_ms = 0  # message already queued; re-read immediately

    def inbox_size(self, sub_id: str) -> int:
        return int(self._lib.rpb_inbox_size(self._h, sub_id.encode()))


class PyPubsub:
    """Pure-Python twin (decision parity; tests run both)."""

    MAX_INBOX = 10_000

    def __init__(self):
        self._lock = threading.Lock()
        self._interests: Dict[str, Set[Tuple[str, str]]] = {}
        self._inboxes: Dict[str, collections.deque] = {}
        self._cvs: Dict[str, threading.Condition] = {}

    def _cv(self, sub_id: str) -> threading.Condition:
        return self._cvs.setdefault(sub_id, threading.Condition(self._lock))

    def subscribe(self, sub_id: str, channel: str, key: str = "") -> None:
        with self._lock:
            self._interests.setdefault(sub_id, set()).add((channel, key))
            self._inboxes.setdefault(sub_id, collections.deque())
            self._cv(sub_id)

    def unsubscribe(self, sub_id: str, channel: str, key: str = "") -> None:
        with self._lock:
            self._interests.get(sub_id, set()).discard((channel, key))

    def drop_subscriber(self, sub_id: str) -> None:
        with self._lock:
            self._interests.pop(sub_id, None)
            self._inboxes.pop(sub_id, None)
            self._cvs.pop(sub_id, None)

    def publish(self, channel: str, key: str, payload: str) -> int:
        delivered = 0
        with self._lock:
            for sub_id, interests in self._interests.items():
                if (channel, key) in interests or (channel, "") in interests:
                    inbox = self._inboxes[sub_id]
                    if len(inbox) >= self.MAX_INBOX:
                        inbox.popleft()
                    inbox.append((channel, key, payload))
                    self._cvs[sub_id].notify_all()
                    delivered += 1
        return delivered

    def poll(self, sub_id: str, timeout: float = 1.0
             ) -> Optional[Tuple[str, str, str]]:
        with self._lock:
            if sub_id not in self._inboxes:
                return None
            inbox = self._inboxes[sub_id]
            if not inbox:
                self._cv(sub_id).wait_for(lambda: bool(inbox), timeout)
            return inbox.popleft() if inbox else None

    def inbox_size(self, sub_id: str) -> int:
        with self._lock:
            inbox = self._inboxes.get(sub_id)
            return -1 if inbox is None else len(inbox)


def make_pubsub(use_native: bool = True):
    if use_native and native_pubsub_available():
        return NativePubsub()
    return PyPubsub()
