"""Pluggable object-spill backends behind URI schemes.

Analog of the reference's external-storage layer
(python/ray/_private/external_storage.py): the raylet's
``LocalObjectManager`` spills primary copies through an
``ExternalStorage`` implementation selected by config — filesystem,
smart_open/S3, or a custom class path — and hands the resulting URL to
the owner, who can later ask ANY node to restore from it. This module
is the ray_tpu twin: every byte of spill IO in ``_private/`` flows
through a :class:`SpillBackend` so the chaos sites
(``spill.write_error`` / ``spill.restore_error``) and the failure
counters observe all of it (enforced by the AST lint in
``tests/test_log_lint.py``).

Schemes
    ``file://<dir>``      per-process spill dir — current behavior; the
                          files die with their daemon (not durable).
    ``session://[<id>]``  the host-shared session directory
                          (``ray_logging.session_dir_for``): survives
                          daemon death, so the head can re-point a
                          restore at any surviving node — or read the
                          file itself.
    ``mock-s3://<bucket>``local-directory stand-in for a remote object
                          store; the real S3/GCS client is left as a
                          :func:`register_spill_backend` registration
                          point (the reference gates smart_open the
                          same way).

Writes are crash-safe everywhere: payload goes to ``<path>.tmp``,
``flush`` + ``fsync``, then an atomic ``os.replace`` — a reader never
observes a torn file, and a daemon killed mid-spill leaves only a
``.tmp`` turd that the next write truncates. A failed write degrades
gracefully (caller keeps the in-memory copy and bumps
``ray_tpu_object_spill_failures_total{op="write"}``); a failed or
truncated read is a *tier miss* — the caller falls down the recovery
hierarchy (replica → spill → lineage) instead of raising into
``get()``.
"""

from __future__ import annotations

import logging
import os
import tempfile
import threading
from typing import Callable, Dict, Optional, Tuple

from ray_tpu._private import chaos

logger = logging.getLogger(__name__)

# uri scheme -> factory(uri) -> SpillBackend; extension point for real
# remote stores (S3/GCS): register a scheme and set
# RAY_TPU_object_spill_uri="s3://bucket/prefix".
_BACKENDS: Dict[str, Callable[[str], "SpillBackend"]] = {}
_LOCK = threading.Lock()


def register_spill_backend(scheme: str,
                           factory: Callable[[str], "SpillBackend"]) -> None:
    """Register a backend factory for a URI scheme (e.g. ``s3``)."""
    with _LOCK:
        _BACKENDS[scheme] = factory


def _split_uri(uri: str) -> Tuple[str, str]:
    scheme, sep, rest = uri.partition("://")
    if not sep:
        raise ValueError(f"not a spill URI: {uri!r}")
    return scheme, rest


class SpillFailure(OSError):
    """A spill write/read that failed (real IO error or injected via the
    ``io_oserror`` chaos kind at ``spill.write_error`` /
    ``spill.restore_error``). Callers degrade, never propagate."""


class SpillBackend:
    """One URI scheme's spill IO. Subclasses define where bytes land;
    the base class owns atomicity, chaos injection, and accounting."""

    #: Does the payload survive the writing daemon's death? Durable
    #: URIs are announced to the head for cross-node restore.
    durable = False
    scheme = "file"

    def __init__(self, root: str):
        self._root = root
        self._made = False

    @property
    def root(self) -> str:
        return self._root

    def _ensure_root(self) -> None:
        if not self._made:
            os.makedirs(self._root, exist_ok=True)
            self._made = True

    def uri_for(self, filename: str) -> str:
        return f"{self.scheme}://{filename}"

    def path_for(self, uri: str) -> str:
        _, rest = _split_uri(uri)
        return os.path.join(self._root, os.path.basename(rest))

    # -- write ------------------------------------------------------------

    def write(self, filename: str, payload) -> str:
        """Atomically persist ``payload`` (bytes or a list of buffers)
        under ``filename``; returns the spill URI. Raises
        :class:`SpillFailure` on any IO error (callers keep the memory
        copy and count the failure)."""
        self._ensure_root()
        path = os.path.join(self._root, os.path.basename(filename))
        tmp = path + ".tmp"
        try:
            if chaos.ACTIVE:
                chaos.maybe_inject("spill.write_error")
            with open(tmp, "wb") as f:
                if isinstance(payload, (bytes, bytearray, memoryview)):
                    f.write(payload)
                else:
                    for part in payload:
                        f.write(part)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as exc:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            _count_failure("write")
            raise SpillFailure(f"spill write of {filename} failed: {exc}") \
                from exc
        return self.uri_for(filename)

    # -- read -------------------------------------------------------------

    def read(self, uri: str, expected_size: int = 0) -> Optional[bytes]:
        """Read a spilled payload back. Returns ``None`` on a tier miss:
        missing file, truncated file (shorter than ``expected_size``),
        or an injected restore error — the caller falls down a tier."""
        return self.read_path(self.path_for(uri), expected_size)

    def read_path(self, path: str, expected_size: int = 0
                  ) -> Optional[bytes]:
        """``read`` for callers whose bookkeeping is path-based (the
        node table records local paths, not URIs). Same tier-miss
        contract and chaos/failure accounting."""
        try:
            if chaos.ACTIVE:
                chaos.maybe_inject("spill.restore_error")
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            _count_failure("restore")
            return None
        if expected_size and len(data) < expected_size:
            _count_failure("restore")
            logger.warning("spilled payload %s truncated (%d < %d bytes)",
                           path, len(data), expected_size)
            return None
        return data

    def read_range(self, uri: str, offset: int, length: int
                   ) -> Optional[bytes]:
        """Read ``length`` bytes at ``offset`` from a spilled payload —
        the byte-range primitive behind sharded-checkpoint resharding
        (a restarted gang pulls only the slices it needs from each
        saved shard, not whole files). Same tier-miss contract as
        :meth:`read`: ``None`` on a missing/short file or an injected
        restore error."""
        path = self.path_for(uri)
        try:
            if chaos.ACTIVE:
                chaos.maybe_inject("spill.restore_error")
            with open(path, "rb") as f:
                data = os.pread(f.fileno(), length, offset)
        except OSError:
            _count_failure("restore")
            return None
        if len(data) < length:
            _count_failure("restore")
            logger.warning(
                "spilled payload %s truncated (%d < %d bytes at +%d)",
                path, len(data), length, offset)
            return None
        return data

    def list_files(self, prefix: str = ""):
        """Filenames under this backend's root starting with ``prefix``
        (``.tmp`` turds excluded) — lets index loaders reconcile what
        storage actually holds against what was committed (orphan-shard
        garbage collection). Returns [] when the root doesn't exist."""
        try:
            names = os.listdir(self._root)
        except OSError:
            return []
        return sorted(n for n in names
                      if n.startswith(prefix) and not n.endswith(".tmp"))

    def size_of(self, uri: str) -> Optional[int]:
        """On-storage byte size of a spilled payload (None if missing)."""
        try:
            return os.stat(self.path_for(uri)).st_size
        except OSError:
            return None

    # -- landing (chunked recv straight to backend storage) ---------------

    def create_landing(self, filename: str, size: int) -> "SpillLanding":
        """An fd-backed landing for a chunked pull that goes straight to
        backend storage (the ``begin_recv`` disk path): chunks land via
        ``pwrite``, ``commit`` fsyncs and atomically renames."""
        self._ensure_root()
        path = os.path.join(self._root, os.path.basename(filename))
        if chaos.ACTIVE:
            chaos.maybe_inject("spill.write_error")
        return SpillLanding(self, path, size, self.uri_for(filename))

    # -- delete / teardown ------------------------------------------------

    def delete(self, uri: str) -> None:
        self.delete_path(self.path_for(uri))

    @staticmethod
    def delete_path(path: str) -> None:
        try:
            os.unlink(path)
        except OSError:
            pass

    def close(self) -> None:
        """Remove the backend root if this backend owns it (per-process
        file:// dirs). Durable backends leave their files for peers."""
        if self.durable:
            return
        try:
            for name in os.listdir(self._root):
                try:
                    os.unlink(os.path.join(self._root, name))
                except OSError:
                    pass
            os.rmdir(self._root)
        except OSError:
            pass


class SpillLanding:
    """fd + pwrite landing used by the dataplane's disk recv path."""

    __slots__ = ("backend", "path", "tmp", "fd", "size", "uri")

    def __init__(self, backend: SpillBackend, path: str, size: int,
                 uri: str):
        self.backend = backend
        self.path = path
        self.tmp = path + ".tmp"
        self.size = size
        self.uri = uri
        self.fd = os.open(self.tmp, os.O_RDWR | os.O_CREAT | os.O_TRUNC,
                          0o600)
        if size:
            os.ftruncate(self.fd, size)

    def pwrite(self, data, offset: int) -> None:
        os.pwrite(self.fd, data, offset)

    def commit(self) -> None:
        os.fsync(self.fd)
        os.close(self.fd)
        os.replace(self.tmp, self.path)

    def abort(self) -> None:
        try:
            os.close(self.fd)
        except OSError:
            pass
        try:
            os.unlink(self.tmp)
        except OSError:
            pass


class FileSpillBackend(SpillBackend):
    """``file://`` — a plain per-process directory (seed behavior)."""

    durable = False
    scheme = "file"

    def uri_for(self, filename: str) -> str:
        # Absolute-path URIs so a same-host reader could still find the
        # file; durability is still "no" because close() removes it.
        return "file://" + os.path.join(self._root,
                                        os.path.basename(filename))

    def path_for(self, uri: str) -> str:
        _, rest = _split_uri(uri)
        return rest if os.path.isabs(rest) else \
            os.path.join(self._root, os.path.basename(rest))


class SessionSpillBackend(SpillBackend):
    """``session://<session_id>/<file>`` — the host-shared session dir.

    Survives daemon death: the directory belongs to the cluster session
    (``ray_logging.session_dir_for``), so after SIGKILLing the spilling
    daemon any process that knows the URI — the head included — can
    restore the payload without re-running the producer."""

    durable = True
    scheme = "session"

    def __init__(self, session_id: str):
        from ray_tpu._private import ray_logging
        self.session_id = session_id
        super().__init__(
            os.path.join(ray_logging.session_dir_for(session_id), "spill"))

    def uri_for(self, filename: str) -> str:
        return f"session://{self.session_id}/{os.path.basename(filename)}"

    def path_for(self, uri: str) -> str:
        from ray_tpu._private import ray_logging
        _, rest = _split_uri(uri)
        sid, _, name = rest.partition("/")
        if not name:  # bare session://<file> — ours
            sid, name = self.session_id, sid
        return os.path.join(ray_logging.session_dir_for(sid), "spill",
                            os.path.basename(name))


class MockS3SpillBackend(SpillBackend):
    """``mock-s3://<bucket>/<key>`` — a local-directory stand-in for a
    remote object store, keeping the URI/restore contract of a real one
    (any node resolves the same bucket dir). Swap in real S3/GCS via
    ``register_spill_backend("s3", ...)``."""

    durable = True
    scheme = "mock-s3"

    def __init__(self, bucket: str = "spill"):
        self.bucket = bucket or "spill"
        root = os.environ.get("RAY_TPU_MOCK_S3_DIR") or os.path.join(
            tempfile.gettempdir(), "ray_tpu-mock-s3")
        super().__init__(os.path.join(root, self.bucket))

    def uri_for(self, filename: str) -> str:
        return f"mock-s3://{self.bucket}/{os.path.basename(filename)}"

    def path_for(self, uri: str) -> str:
        _, rest = _split_uri(uri)
        bucket, _, name = rest.partition("/")
        if not name:
            bucket, name = self.bucket, bucket
        root = os.environ.get("RAY_TPU_MOCK_S3_DIR") or os.path.join(
            tempfile.gettempdir(), "ray_tpu-mock-s3")
        return os.path.join(root, bucket, os.path.basename(name))


def backend_for_uri(base_uri: str, session_id: str = "",
                    fallback_dir: str = "") -> SpillBackend:
    """Build the backend named by ``object_spill_uri``.

    ``base_uri`` forms: empty (file:// over ``fallback_dir``),
    ``file:///abs/dir``, ``session://`` (uses ``session_id``),
    ``session://<explicit-id>``, ``mock-s3://<bucket>``, or any
    registered custom scheme."""
    if not base_uri:
        return FileSpillBackend(fallback_dir or os.path.join(
            tempfile.gettempdir(), f"ray_tpu_spill_{os.getpid()}"))
    scheme, rest = _split_uri(base_uri)
    with _LOCK:
        factory = _BACKENDS.get(scheme)
    if factory is not None:
        return factory(base_uri)
    if scheme == "file":
        return FileSpillBackend(rest or fallback_dir)
    if scheme == "session":
        sid = rest.strip("/") or session_id
        if not sid:
            raise ValueError(
                "session:// spill URI needs a session id (register with "
                "the head first, or pass session://<id>)")
        return SessionSpillBackend(sid)
    if scheme == "mock-s3":
        return MockS3SpillBackend(rest.strip("/"))
    raise ValueError(
        f"no spill backend registered for scheme {scheme!r} "
        f"(register one with ray_tpu._private.spill.register_spill_backend)")


def reader_for_uri(uri: str) -> Optional[SpillBackend]:
    """A backend capable of reading ``uri`` — used by restore paths that
    hold only a URI (head-side restore after the spilling daemon died,
    or a node restoring a peer's durable spill)."""
    try:
        scheme, rest = _split_uri(uri)
    except ValueError:
        return None
    with _LOCK:
        factory = _BACKENDS.get(scheme)
    try:
        if factory is not None:
            return factory(uri)
        if scheme == "file":
            return FileSpillBackend(os.path.dirname(rest) or ".")
        if scheme == "session":
            sid = rest.partition("/")[0]
            return SessionSpillBackend(sid) if sid else None
        if scheme == "mock-s3":
            return MockS3SpillBackend(rest.partition("/")[0])
    except (ValueError, OSError):
        return None
    return None


def read_uri(uri: str, expected_size: int = 0) -> Optional[bytes]:
    """Restore a payload from any spill URI (tier miss -> ``None``)."""
    backend = reader_for_uri(uri)
    if backend is None:
        return None
    return backend.read(uri, expected_size)


def _count_failure(op: str) -> None:
    try:
        from ray_tpu._private import builtin_metrics, events
        builtin_metrics.object_spill_failures().inc(tags={"op": op})
        # Journal-worthy: spill IO failing is how durable tiers silently
        # degrade to lineage re-execution. Rides the next metrics tick.
        events.emit("spill", f"spill backend {op} failure",
                    severity="warning", labels={"op": op})
    except Exception:  # noqa: BLE001 - metrics must never break spill IO
        pass
