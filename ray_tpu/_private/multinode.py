"""Multi-process cluster substrate: head server + node daemons over TCP.

The real-process analog of the reference's control plane (SURVEY.md §2.1:
gRPC `src/ray/rpc/` + GCS server + raylets): the driver process acts as
head (owner of all objects, scheduler authority — the collapsed
GCS/owner model this runtime uses throughout), and **node daemons** are
separate OS processes (possibly on other hosts) that register resources
and execute user code pushed to them. The wire protocol is
length-prefixed cloudpickle frames over one persistent TCP connection per
node — the moral equivalent of the reference's PushTask gRPC stream, with
connection death standing in for raylet health-check failure
(gcs_health_check_manager.h): the head converts a dropped connection into
`Runtime.remove_node`, which drives the existing retry / actor-restart /
lineage-reconstruction machinery.

Execution model: scheduling, retries, and the object DIRECTORY stay on
the head; only the *user-code call* (`fn(*args)`, `cls(*args)`,
`instance.method(*args)`) crosses the wire. Normal tasks dispatch
ASYNC — `execute_task_async` + per-connection completion drainers, no
head thread parked per in-flight call (reference: callback-driven
direct task transport) — and same-class tasks stream onto worker
LEASES whose daemon-side serial executors order execution locally
(one accounted acquisition ↔ one running task; blocked nested gets
spill/unspill the queue). Actor calls hold one head executor thread
per actor-concurrency slot — the ordering authority, mirroring the
reference's one-worker-per-actor model; thread count scales with
actors, never with queued tasks (1M queued tasks = 3 threads,
tests/test_core.py deep-queue envelope). Small results return inline
in the reply (core_worker.cc PushTaskReply); big results stay
daemon-resident and travel the chunked data plane (dataplane.py), as
do node-resident distributed-ownership puts.

Daemons run actors too: the instance lives in the daemon process
(constructed there), and the head-side actor executor proxies each method
call, preserving per-handle ordering. Daemon death restarts actors
elsewhere through the normal node-death path.
"""

from __future__ import annotations

import contextlib
import logging
import os
import socket
import struct
import threading
import traceback
from time import monotonic as _monotonic
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import chaos as _chaos
from ray_tpu._private import procinfo
from ray_tpu._private import wire as _wire

logger = logging.getLogger(__name__)

_FRAME = struct.Struct(">Q")
_MAX_FRAME = 1 << 34  # 16 GiB sanity bound

#: Shared stateless no-op context: the untraced daemon execute path pays
#: one dict read and zero allocations for tracing.
_NULL_SPAN = contextlib.nullcontext()


def _trace_span(ctx: Optional[dict], name: str, stage: str):
    """A continue_context span when the request carries a sampled trace
    context (propagated from the driver), the shared no-op otherwise."""
    if ctx is None:
        return _NULL_SPAN
    from ray_tpu.util import tracing
    return tracing.continue_context(ctx, name, {"stage": stage})


class RemoteNodeDiedError(RuntimeError):
    """The node connection dropped while a call was in flight. NOT a
    TaskError: the runtime treats it as a system failure (node death),
    and the in-flight spec is invalidated/retried by remove_node."""


# ---------------------------------------------------------------------------
# Framing
# ---------------------------------------------------------------------------


def _send_frame_parts(sock: socket.socket, parts,
                      lock: Optional[threading.Lock] = None) -> None:
    """Length-prefix and write a frame given as buffer parts — payload
    buffers go to the kernel by scatter-gather (channel.sock_send_parts)
    without being joined behind the length prefix."""
    from ray_tpu._private.channel import sock_send_parts
    total = _parts_size(parts)
    hdr = _FRAME.pack(total)
    if lock is not None:
        with lock:
            sock_send_parts(sock, (hdr, *parts))
    else:
        sock_send_parts(sock, (hdr, *parts))


def _send_frame(sock: socket.socket, payload: bytes,
                lock: Optional[threading.Lock] = None) -> None:
    _send_frame_parts(sock, (payload,), lock)


def _send_frame_best_effort(sock: socket.socket, payload: bytes,
                            lock: Optional[threading.Lock] = None) -> bool:
    """Send a frame whose loss is acceptable (rejection notices,
    fire-and-forget teardown messages to possibly-dead peers). Returns
    False instead of raising on transport failure. Frames that must
    arrive go through a ResilientChannel / _CoalescingSender instead —
    the log lint bans ad-hoc OSError suppression around _send_frame."""
    try:
        _send_frame(sock, payload, lock)
        return True
    except OSError:
        return False


def _close_quiet(sock: socket.socket) -> None:
    try:
        sock.close()
    except OSError:
        pass


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> bytes:
    (length,) = _FRAME.unpack(_recv_exact(sock, _FRAME.size))
    if length > _MAX_FRAME:
        raise ConnectionError(f"frame of {length} bytes exceeds bound")
    return _recv_exact(sock, length)


def _dumps(obj: Any) -> bytes:
    from ray_tpu._private import serialization
    return serialization.serialize(obj)


def _loads(data: bytes) -> Any:
    from ray_tpu._private import serialization
    return serialization.deserialize(data)


def _dumps_parts(obj: Any) -> list:
    """Serialize into bytes-like parts (serialization.serialize_parts):
    big array payloads keep their data buffers as views so the object
    table can lay them into the arena with one memcpy."""
    from ray_tpu._private import serialization
    return serialization.serialize_parts(obj)


def _parts_size(parts) -> int:
    # memoryview len() counts elements, not bytes (non-'B' formats).
    return sum(p.nbytes if isinstance(p, memoryview) else len(p)
               for p in parts)


def _join_parts(parts: list) -> bytes:
    if len(parts) == 1 and isinstance(parts[0], bytes):
        return parts[0]
    return b"".join(bytes(p) for p in parts)


def _encode_frame_parts(msg: dict) -> list:
    """Typed binary layout for hot-path ops (wire.py phase 2) as a part
    list — payload bytes stay by reference — pickle envelope for
    everything else."""
    parts = _wire.encode_typed_parts(msg)
    return parts if parts is not None else [_dumps(msg)]


def _encode_frame(msg: dict) -> bytes:
    """Joined form of :func:`_encode_frame_parts`."""
    return _join_parts(_encode_frame_parts(msg))


def _decode_frames(raw: bytes) -> list:
    """Decode one wire frame into its message dict(s): binary batches
    and legacy dict batches both flatten to a list."""
    parts = _wire.decode_batch(raw)
    if parts is not None:
        return [_decode_one(p) for p in parts]
    msg = _decode_one(raw)
    if isinstance(msg, dict) and msg.get("type") in ("task_batch",
                                                     "reply_batch"):
        # Legacy dict batch: validate the envelope before touching its
        # fields — a drifted peer fails with the exact field name.
        _wire.validate_message(msg)
        return list(msg["msgs"])
    return [msg]


def _decode_one(raw: bytes):
    msg = _wire.decode_typed(raw)
    return msg if msg is not None else _loads(raw)


def _args_are_plain(args, kwargs) -> bool:
    """True when no top-level arg is a data-plane marker (the only
    place the head ever puts one — see Runtime._resolve_args)."""
    from ray_tpu._private.dataplane import ObjectMarker
    markers = (ObjectMarker, RemoteArgMarker)
    return not (any(isinstance(a, markers) for a in args)
                or any(isinstance(v, markers) for v in kwargs.values()))


class _CoalescingSender:
    """Single writer for one control socket. Callers enqueue message
    dicts; the sender thread writes them, coalescing whatever has
    accumulated into ONE ``batch_type`` frame (reference: the gRPC
    transport's stream batching amortizes per-message overhead the same
    way). Under load this collapses N pickle dumps + N sendall syscalls
    into one of each; when idle the thread wakes per message and sends
    it solo, so single-task latency pays nothing.

    All writes for the socket MUST go through this object once it is
    attached — a direct ``_send_frame`` from another thread would
    interleave bytes mid-frame. The enqueue lock also serializes
    ``resolver`` callbacks (fn_bytes shipping decisions), which makes
    the decide-and-order step atomic across submitting threads.
    """

    MAX_BATCH = 64            # messages per batch frame
    SOLO_BYTES = 256 * 1024   # payloads this big travel alone
    MAX_BATCH_BYTES = 1 << 20  # cumulative payload cap per batch
    QUEUE_CAP_BYTES = 64 << 20  # backpressure: block senders past this

    def __init__(self, transport, batch_type: str,
                 on_fail=None, name: str = "sender"):
        if isinstance(transport, socket.socket):
            transport = _SocketTransport(transport)
        self._transport = transport
        self._batch_type = batch_type
        self._on_fail = on_fail
        from collections import deque
        self._dq: Any = deque()
        self._cv = threading.Condition()
        self._closed = False
        self._queued_bytes = 0
        self._sending = False  # a popped batch is being written
        self._thread = threading.Thread(
            target=self._run, name=f"ray_tpu-{name}", daemon=True)
        self._thread.start()

    def send(self, msg: dict, resolver=None, nbytes: int = 0) -> bool:
        """Enqueue; returns False if the sender is closed. ``resolver``
        runs under the enqueue lock (may mutate msg, may raise — in
        which case nothing is enqueued). ``nbytes`` is a payload-size
        hint for batch splitting and backpressure."""
        with self._cv:
            while (self._queued_bytes > self.QUEUE_CAP_BYTES
                   and not self._closed):
                self._cv.wait(1.0)
            if self._closed:
                return False
            if resolver is not None:
                resolver(msg)
            self._dq.append((msg, nbytes))
            self._queued_bytes += nbytes
            self._cv.notify_all()
        return True

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def flush(self, timeout: float = 1.0) -> None:
        """Best-effort wait for the queue to drain (shutdown paths)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        with self._cv:
            while (self._dq or self._sending) and \
                    _time.monotonic() < deadline:
                self._cv.wait(0.05)

    def _pop_batch(self):
        batch = []
        total = 0
        while self._dq and len(batch) < self.MAX_BATCH:
            msg, nb = self._dq[0]
            if batch and (nb >= self.SOLO_BYTES
                          or total + nb > self.MAX_BATCH_BYTES):
                break
            self._dq.popleft()
            self._queued_bytes -= nb
            batch.append(msg)
            total += nb
            if nb >= self.SOLO_BYTES:
                break
        return batch

    def _run(self) -> None:
        from ray_tpu._private.channel import ChannelBroken
        while True:
            with self._cv:
                while not self._dq and not self._closed:
                    self._cv.wait()
                if not self._dq:
                    return  # closed and drained
                batch = self._pop_batch()
                self._sending = True
                self._cv.notify_all()  # backpressured senders re-check
            try:
                if len(batch) == 1:
                    self._transport.send_parts(
                        *_encode_frame_parts(batch[0]))
                else:
                    # Binary batch: each message encodes ONCE (typed or
                    # pickle) into a part list; the batch frame is just
                    # those parts behind per-frame length prefixes — the
                    # accumulated payload bytes are never re-joined.
                    self._transport.send_parts(*_wire.encode_batch_parts(
                        [_encode_frame_parts(m) for m in batch]))
            except ChannelBroken:
                # The frame already sits in the channel's resend ring
                # and is replayed by the resume attach; park until the
                # channel recovers. Only a closed channel / exhausted
                # reconnect window escalates to on_fail (node death).
                self._done_sending()
                if self._transport.wait_recovered():
                    continue
                self._fail()
                return
            except OSError:
                self._done_sending()
                self._fail()
                return
            except Exception:  # noqa: BLE001 - one poisoned msg must
                # not kill the connection: retry each solo, drop the
                # one that cannot serialize.
                if not self._send_solo(batch):
                    return
            self._done_sending()

    def _send_solo(self, batch) -> bool:
        from ray_tpu._private.channel import ChannelBroken
        for msg in batch:
            try:
                self._transport.send_parts(*_encode_frame_parts(msg))
            except ChannelBroken:
                if self._transport.wait_recovered():
                    continue  # ringed frame replays on resume
                self._done_sending()
                self._fail()
                return False
            except OSError:
                self._done_sending()
                self._fail()
                return False
            except Exception:
                logger.exception(
                    "dropping unserializable control frame %s",
                    msg.get("type"))
        return True

    def _fail(self) -> None:
        self.close()
        if self._on_fail is not None:
            try:
                self._on_fail()
            except Exception:  # noqa: BLE001 - teardown
                logger.exception("sender failure handler")

    def _done_sending(self) -> None:
        with self._cv:
            self._sending = False
            self._cv.notify_all()


class _SocketTransport:
    """Raw-socket transport for :class:`_CoalescingSender` users whose
    channels do not resume (client sessions, worker IPC)."""

    __slots__ = ("_sock", "_lock")

    def __init__(self, sock: socket.socket, lock=None):
        self._sock = sock
        self._lock = lock

    def send_frame(self, payload: bytes) -> None:
        _send_frame_parts(self._sock, (payload,), self._lock)

    def send_parts(self, *parts) -> None:
        _send_frame_parts(self._sock, parts, self._lock)

    def wait_recovered(self) -> bool:
        return False


# ---------------------------------------------------------------------------
# Head side
# ---------------------------------------------------------------------------


class _Pending:
    """A blocked caller (event mode) or an async continuation (callback
    mode — the reference's ClientCallManager completion path: no head
    thread is parked while the daemon works)."""

    __slots__ = ("event", "reply", "callback")

    def __init__(self, callback=None):
        self.callback = callback
        self.event = None if callback is not None else threading.Event()
        self.reply: Optional[dict] = None


class NodeConnection:
    """Head-side handle to one node daemon: request/reply multiplexing
    over the persistent socket (analog of the reference's per-raylet
    rpc client with a ClientCallManager)."""

    def __init__(self, sock: socket.socket, address: Tuple[str, int],
                 resources: Dict[str, float], labels: Optional[dict],
                 object_addr: Optional[Tuple[str, int]] = None,
                 store_name: Optional[str] = None,
                 reconnect_window_s: float = 30.0,
                 resend_ring_bytes: int = 64 << 20,
                 ack_every: Optional[int] = None,
                 ack_flush_ms: Optional[int] = None):
        from ray_tpu._private.channel import ResilientChannel
        self._sock = sock
        # Resilient session channel: all post-handshake traffic (both
        # directions) flows through it; a transient socket failure
        # parks senders until the daemon re-dials and resumes instead
        # of cascading into remove_node.
        self.channel = ResilientChannel(
            sock, site="head", ring_bytes=resend_ring_bytes,
            window_s=reconnect_window_s, ack_every=ack_every,
            ack_flush_ms=ack_flush_ms)
        import uuid
        # Capability for the resume handshake: the daemon must present
        # it to re-attach, so a stray/imposter dial cannot hijack a
        # session.
        self.channel_token = uuid.uuid4().hex
        self.address = address
        self.resources = resources
        self.labels = labels or {}
        # The daemon's object-server endpoint (peer-to-peer data plane)
        # and shm arena name (same-host zero-copy attach).
        self.object_addr = tuple(object_addr) if object_addr else None
        self.store_name = store_name
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._req_counter = 0
        self._closed = False
        self._shipped_functions: set = set()
        self.node_id = None  # set at registration
        self._on_death = None
        # Set by HeadServer: a broken session channel wakes the
        # membership loop NOW — a SIGKILLed daemon is probed (and
        # declared dead) in probe-timeout time, not on the next sweep.
        self.on_channel_broken = None
        # Runtime hooks for daemon-pushed frames (no req_id — the recv
        # loop routes them here instead of the pending table).
        self.on_log_batch = None
        self.on_metrics_batch = None
        self.on_profile_batch = None
        self.on_flow_batch = None
        self.on_object_spilled = None
        self.on_object_unspilled = None
        # Dedicated liveness socket (see HeadServer._health_check_loop):
        # pings must not share the data channel — large frames or a full
        # send buffer would stall them and fake a death (or hide one).
        self.health_sock: Optional[socket.socket] = None
        import time
        self.registered_at = time.monotonic()
        # Updated by recv_loop on every inbound frame batch; the head's
        # health sweep reads it as proof of life when pings time out.
        self.last_frame_at = self.registered_at
        # Chaos injection (reference: RAY_testing_* fault flags): each
        # request fails with this probability — exercised by the chaos
        # tests to prove retries survive a flaky control plane.
        self.rpc_failure_pct = 0
        import random
        self._chaos_rng = random.Random(0xC4A05)
        # Bytes of object payload that transited the HEAD for this node
        # (driver gets). Node-to-node pulls never touch this counter —
        # tests assert the head is out of the task-arg data path.
        self.head_fetch_bytes = 0
        # Dedicated completion drainer: recv_loop only enqueues, so the
        # reply stream never stalls behind a slow continuation, while
        # completions skip a shared pool's submit/wakeup overhead
        # (measured ~40% of remote-task throughput at 5k+ tasks/s).
        import queue as _queue
        self._completion_q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._completion_thread: Optional[threading.Thread] = None
        self._drainer_dead = False  # guarded by self._lock
        # Single-writer coalescing sender: every outbound frame for this
        # daemon goes through it (task submits batch under load).
        self._sender = _CoalescingSender(
            self.channel, "task_batch", on_fail=self.close,
            name=f"send-{address[1]}")

    # -- plumbing --------------------------------------------------------

    def _next_req(self) -> int:
        with self._lock:
            self._req_counter += 1
            return self._req_counter

    def _request(self, msg: dict, fn_resolver=None,
                 timeout: Optional[float] = None) -> dict:
        """Send a request and block until its reply (or node death).

        ``fn_resolver`` (if given) decides the message's fn_bytes field
        *inside the send lock*: frames share one socket, so deciding
        "already shipped" and sending must be atomic — otherwise a
        concurrent first use could send fn_bytes=None ahead of the frame
        actually carrying the bytes."""
        req_id = self._next_req()
        msg["req_id"] = req_id
        # Outbound control frames are schema-checked at the SOURCE: a
        # drifted field fails here with the offending name, not on the
        # daemon as an opaque handler error (reference: the proto
        # contract enforces this at compile time).
        _wire.validate_message(msg)
        waiter = _Pending()
        with self._lock:
            if self._closed:
                raise RemoteNodeDiedError(
                    f"node {self.address} connection is closed")
            self._pending[req_id] = waiter
        resolver = None
        if fn_resolver is not None:
            def resolver(m, _fr=fn_resolver):
                m["fn_bytes"] = _fr()
        try:
            sent = self._sender.send(
                msg, resolver=resolver,
                nbytes=len(msg.get("payload") or b""))
        except BaseException:
            with self._lock:
                self._pending.pop(req_id, None)
            raise
        if not sent:
            with self._lock:
                self._pending.pop(req_id, None)
            raise RemoteNodeDiedError(
                f"node {self.address} connection is closed")
        if not waiter.event.wait(timeout):
            with self._lock:
                self._pending.pop(req_id, None)
            raise TimeoutError(
                f"node {self.address} did not reply to "
                f"{msg.get('type')} within {timeout}s")
        reply = waiter.reply
        if reply is None or reply.get("type") == "died":
            raise RemoteNodeDiedError(
                f"node {self.address} died while a call was in flight")
        return reply

    def _fire_and_forget(self, msg: dict) -> None:
        """Send with req_id 0 — the daemon's reply (if any) is dropped by
        the recv loop. Never blocks on the daemon (GC/teardown paths)."""
        msg["req_id"] = 0
        _wire.validate_message(msg)
        self._sender.send(msg)  # closed sender: daemon is gone anyway

    def recv_loop(self) -> None:
        """Reply pump; runs on a daemon thread owned by HeadServer.
        Callback-mode completions are handed to this connection's
        drainer thread so a slow continuation (deserialize + store +
        dispatch) never stalls the reply stream."""
        from ray_tpu._private.channel import ChannelBroken, ChannelClosed
        try:
            while True:
                try:
                    raw = self.channel.recv_frame()
                except ChannelBroken:
                    # Transient transport failure: the daemon re-dials
                    # and resumes within the reconnect window. Node
                    # death fires only when the window closes (or the
                    # membership loop confirms the process is gone —
                    # woken immediately via the hook).
                    hook = self.on_channel_broken
                    if hook is not None:
                        hook()
                    if self.channel.wait_recovered():
                        continue
                    break
                except ChannelClosed:
                    break
                replies = _decode_frames(raw)
                # Liveness evidence for the health sweep: a node whose
                # data channel is actively delivering frames is alive no
                # matter how starved its ping thread is (GB-scale
                # transfers on an oversubscribed host can stall the
                # health channel long past the miss threshold).
                self.last_frame_at = _monotonic()
                for reply in replies:
                    kind = reply.get("type")
                    if kind in ("log_batch", "metrics_batch",
                                "profile_batch", "flow_batch",
                                "object_spilled", "object_unspilled"):
                        # Daemon-initiated push, not a reply: hand to
                        # the runtime's fan-out and move on.
                        handler = {
                            "log_batch": self.on_log_batch,
                            "metrics_batch": self.on_metrics_batch,
                            "profile_batch": self.on_profile_batch,
                            "flow_batch": self.on_flow_batch,
                            "object_spilled": self.on_object_spilled,
                            "object_unspilled": self.on_object_unspilled,
                        }[kind]
                        if handler is not None:
                            try:
                                handler(self, reply)
                            except Exception:  # noqa: BLE001
                                logger.exception("%s handling failed",
                                                 kind)
                        del reply
                        continue
                    with self._lock:
                        waiter = self._pending.pop(
                            reply.get("req_id"), None)
                    if waiter is not None:
                        waiter.reply = reply
                        if waiter.callback is not None:
                            self._dispatch_completion(waiter.callback,
                                                      reply)
                        else:
                            waiter.event.set()
                    # Drop locals NOW: an idle connection must not pin
                    # the last task's completion (its callback closes
                    # over the spec, whose args hold ObjectRefs — a
                    # refcount leak).
                    del waiter, reply
                del replies
        except (ConnectionError, OSError):
            pass
        finally:
            self.close()

    def _dispatch_completion(self, callback, reply) -> None:
        with self._lock:
            if not self._drainer_dead:
                if self._completion_thread is None:
                    self._completion_thread = threading.Thread(
                        target=self._drain_completions,
                        name=f"ray_tpu-completions-{self.address[1]}",
                        daemon=True)
                    self._completion_thread.start()
                # Enqueue under the lock: the drainer flips _drainer_dead
                # under the same lock BEFORE its final drain, so nothing
                # can land behind the sentinel unseen.
                self._completion_q.put((callback, reply))
                return
        self._run_completion(callback, reply)  # drainer gone: inline

    def _run_completion(self, callback, reply) -> None:
        from ray_tpu._private.event_stats import GLOBAL
        try:
            with GLOBAL.timed("head.task_completion"):
                callback(reply)
        except Exception:  # noqa: BLE001 - continuations must not kill
            logger.exception("remote-task completion failed")

    def _drain_completions(self) -> None:
        import queue as _queue
        while True:
            item = self._completion_q.get()
            if item is None:
                with self._lock:
                    self._drainer_dead = True
                # Anything enqueued before the flag flip is already in
                # the queue: drain it, THEN exit (no lost completions).
                while True:
                    try:
                        item = self._completion_q.get_nowait()
                    except _queue.Empty:
                        return
                    if item is not None:
                        self._run_completion(*item)
                    del item
            else:
                self._run_completion(*item)
                del item  # see recv_loop: no ref pinning

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        on_death = self._on_death
        if on_death is not None:
            # Node-death bookkeeping FIRST (invalidate + retry in-flight
            # specs), THEN wake blocked callers so they observe
            # spec.invalidated and discard instead of double-retrying.
            try:
                on_death(self)
            except Exception:  # noqa: BLE001 - never strand waiters
                logger.exception("remote-node death handler failed")
        for waiter in pending:
            waiter.reply = {"type": "died"}
            if waiter.callback is not None:
                self._dispatch_completion(waiter.callback, waiter.reply)
            else:
                waiter.event.set()
        self.channel.close()  # wakes parked senders/receivers, closes sock
        if self.health_sock is not None:
            try:
                self.health_sock.close()
            except OSError:
                pass
        # After the died-completions above: drainer exits once they ran.
        self._completion_q.put(None)
        self._sender.close()

    # -- user-code proxies ----------------------------------------------

    def _function_payload(self, fn_id: bytes, functions) -> Optional[bytes]:
        if fn_id in self._shipped_functions:
            return None
        try:
            payload = functions.get_bytes(fn_id)
        except KeyError:
            raise ValueError(
                "This function/class captured objects that cannot be "
                "serialized, so it cannot run on a remote node. Make it "
                "importable/picklable, or pin it to the head node.")
        self._shipped_functions.add(fn_id)
        return payload

    def _unpack(self, reply: dict, name: str) -> Any:
        if reply["ok"]:
            if "mismatch_desc" in reply:
                return MismatchedReturn(reply["mismatch_desc"])
            if "stored_key" in reply:
                return RemoteValueStub(self, reply["stored_key"],
                                       reply["size"])
            if "parts" in reply:
                # Multi-return split: each element is inline or a
                # daemon-resident stub of its own.
                return [
                    RemoteValueStub(self, p["stored_key"], p["size"])
                    if "stored_key" in p else _loads(p["value"])
                    for p in reply["parts"]]
            return _loads(reply["value"])
        from ray_tpu.exceptions import TaskError
        exc, remote_tb = _loads(reply["error"])
        raise TaskError(exc, remote_tb, name)

    def execute_task_async(self, spec, functions, args, kwargs,
                           store_limit: int, callback,
                           lease_id: Optional[str] = None,
                           class_id: Optional[str] = None) -> None:
        """Send an execute_task request whose reply is delivered to
        ``callback(reply_dict)`` on the completion pool — no head thread
        blocks while the daemon works (the thread-per-call fix; the
        reference's CoreWorkerClient is equally callback-driven). Node
        death delivers ``{"type": "died"}``; chaos injection and send
        failures deliver the same (system failure → retry path)."""
        if self.rpc_failure_pct and \
                self._chaos_rng.random() * 100 < self.rpc_failure_pct:
            self._dispatch_completion(callback, {"type": "died",
                                                 "chaos": True})
            return
        req_id = self._next_req()
        waiter = _Pending(callback)
        msg = {
            "type": "execute_task",
            "req_id": req_id,
            "fn_id": spec.function_id,
            "payload": _dumps((args, kwargs)),
            "name": spec.name,
            "task_id": spec.task_id.hex(),
            "runtime_env": spec.runtime_env,
            "tpu_ids": getattr(spec, "_tpu_ids", None),
            "num_cpus": float(getattr(spec, "resources", {}).get(
                "CPU", 1.0) or 0.0),
            "store_limit": store_limit,
        }
        if isinstance(spec.num_returns, int) and spec.num_returns > 1:
            msg["num_returns"] = spec.num_returns
        trace_ctx = getattr(spec, "trace_ctx", None)
        if trace_ctx is not None:
            # Cross-process propagation: the daemon parents its execute
            # span to the head-side submit span (extra wire fields are
            # additive — schema validation allows them).
            msg["trace_ctx"] = trace_ctx
        if lease_id is not None:
            msg["lease_id"] = lease_id
        if class_id is not None:
            msg["class_id"] = class_id
        if _args_are_plain(args, kwargs):
            # No object markers anywhere at top level: the daemon can
            # forward the payload bytes to its worker subprocess without
            # the unpickle→resolve→repickle round (markers only ever
            # appear at top level — _resolve_args resolves there).
            msg["plain_args"] = True
        _wire.validate_message(msg)
        with self._lock:
            closed = self._closed
            if not closed:
                self._pending[req_id] = waiter
        if closed:
            # OUTSIDE self._lock: _dispatch_completion re-takes it (the
            # lock is not reentrant).
            self._dispatch_completion(callback, {"type": "died"})
            return
        def resolver(m):
            m["fn_bytes"] = self._function_payload(
                spec.function_id, functions)

        try:
            sent = self._sender.send(msg, resolver=resolver,
                                     nbytes=len(msg["payload"]))
        except ValueError:
            with self._lock:
                self._pending.pop(req_id, None)
            raise  # unpicklable function: a USER error, raise inline
        except BaseException:
            with self._lock:
                self._pending.pop(req_id, None)
            raise
        if not sent:
            with self._lock:
                self._pending.pop(req_id, None)
            self._dispatch_completion(callback, {"type": "died"})

    def execute_task(self, spec, functions, args, kwargs,
                     store_limit: int = 0) -> Any:
        # Chaos fires ONLY here: the normal-task submit path absorbs the
        # injected failure through the system-retry budget. Actor calls,
        # creation, and fetches have no per-request retry to hide behind,
        # so injecting there would turn chaos into user-visible errors.
        if self.rpc_failure_pct and \
                self._chaos_rng.random() * 100 < self.rpc_failure_pct:
            raise RemoteNodeDiedError(
                f"injected RPC failure (testing_rpc_failure_pct="
                f"{self.rpc_failure_pct})")
        msg = {
            "type": "execute_task",
            "fn_id": spec.function_id,
            "payload": _dumps((args, kwargs)),
            "name": spec.name,
            "task_id": spec.task_id.hex(),
            "runtime_env": spec.runtime_env,
            "tpu_ids": getattr(spec, "_tpu_ids", None),
            "store_limit": store_limit,
            "num_returns": (spec.num_returns if
                            isinstance(spec.num_returns, int) else 1),
        }
        trace_ctx = getattr(spec, "trace_ctx", None)
        if trace_ctx is not None:
            msg["trace_ctx"] = trace_ctx
        reply = self._request(msg, fn_resolver=lambda: self._function_payload(
            spec.function_id, functions))
        return self._unpack(reply, spec.name)

    def fetch_object(self, key: str,
                     timeout: Optional[float] = None) -> bytes:
        t0 = _monotonic()
        reply = self._request({"type": "fetch_object", "key": key},
                              timeout=timeout)
        from ray_tpu._private import flow
        if not reply["ok"]:
            try:
                flow.global_flow_recorder().record(
                    key=key, nbytes=0, duration_s=_monotonic() - t0,
                    direction="in",
                    peer=self.object_addr or self.address,
                    outcome="error")
            except Exception:  # noqa: BLE001 - accounting only
                pass
            exc, remote_tb = _loads(reply["error"])
            raise exc
        self.head_fetch_bytes += len(reply["raw"])
        # Head-side fetches ride the session channel, not the dataplane
        # pull path — they are object transfers all the same, so they
        # land in the flow ledger with the daemon as src.
        try:
            flow.global_flow_recorder().record(
                key=key, nbytes=len(reply["raw"]),
                duration_s=_monotonic() - t0, direction="in",
                peer=self.object_addr or self.address)
        except Exception:  # noqa: BLE001 - accounting only
            pass
        return reply["raw"]

    def free_object(self, key: str) -> None:
        self._fire_and_forget({"type": "free_object", "key": key})

    def adopt_object(self, key: str, size: int) -> bool:
        """Ask the daemon to take BOOKKEEPING ownership of an arena
        entry a sibling worker process wrote directly into the shared
        shm (distributed-ownership puts): registers its size so spill
        liveness sees it, and confirms the payload is still resident.
        False = already evicted/absent — the caller must fall back."""
        reply = self._request({"type": "adopt_object", "key": key,
                              "size": int(size)})
        return bool(_loads(reply["value"]))

    def push_object(self, key: str, size: int, *,
                    data: Optional[bytes] = None, parent=None, alts=(),
                    wait_timeout_s: float = 60.0,
                    timeout: Optional[float] = None) -> dict:
        """Tree-broadcast directive: replicate ``key`` onto this daemon.
        ``data`` seeds the payload inline (the head feeding its direct
        tree children); otherwise the daemon blocking-waits on
        ``parent``'s object server and pulls, re-parenting through
        ``alts`` if the parent dies mid-broadcast. Blocks until the
        daemon acks the landed copy — the reply IS the completion
        notice that updates the head's replica table."""
        reply = self._request({
            "type": "push_object", "key": key, "size": int(size),
            "data": data,
            "parent": list(parent) if parent else None,
            "alts": [list(a) for a in alts],
            "wait_timeout_s": float(wait_timeout_s),
        }, timeout=timeout)
        return _loads(reply["value"]) if reply["ok"] else \
            self._unpack(reply, f"push_object {key}")

    def drop_lease(self, lease_id: str) -> None:
        """The head released this lease: the daemon retires its serial
        executor and returns the pinned worker subprocess to the pool."""
        self._fire_and_forget({"type": "drop_lease", "lease_id": lease_id})

    def reclaim_tasks(self, class_id: str, max_n: int) -> None:
        """Spillback: ask the daemon to hand back up to max_n queued
        tasks of this class (each answers its own req_id with
        reclaimed=True; the head re-dispatches through the normal
        completion path)."""
        self._fire_and_forget({"type": "reclaim_tasks",
                               "class_id": class_id,
                               "max_n": int(max_n)})

    def spill_lease(self, lease_id: str) -> None:
        """The lease's running task blocked in a nested get (its capacity
        was lent out head-side): the daemon moves the lease queue's
        waiting tasks onto free threads, so a pipelined child can never
        deadlock behind its own blocked parent."""
        self._fire_and_forget({"type": "spill_lease", "lease_id": lease_id})

    def unspill_lease(self, lease_id: str) -> None:
        """The blocked get returned (or the blocked task finalized): the
        daemon resumes SERIAL execution for this lease. Frame ordering
        makes this race-free — tasks the head attaches after clearing
        ``blocked`` travel behind this frame, so only the tasks that
        raced the spill window bypass the queue (sanctioned: the lease's
        capacity was lent out for exactly that window)."""
        self._fire_and_forget({"type": "unspill_lease",
                               "lease_id": lease_id})

    def create_actor(self, spec, functions, args, kwargs) -> None:
        reply = self._request({
            "type": "create_actor",
            "actor_id": spec.actor_id.hex(),
            "fn_id": spec.function_id,
            "payload": _dumps((args, kwargs)),
            "name": spec.name,
            "task_id": spec.task_id.hex(),
            "runtime_env": spec.runtime_env,
            "tpu_ids": getattr(spec, "_tpu_ids", None),
        }, fn_resolver=lambda: self._function_payload(
            spec.function_id, functions))
        self._unpack(reply, f"{spec.name}.__init__")

    def call_actor_method(self, actor_id, method_name, name,
                          args, kwargs, store_limit: int = 0,
                          num_returns: int = 1,
                          trace_ctx: Optional[dict] = None) -> Any:
        msg = {
            "type": "actor_call",
            "actor_id": actor_id.hex(),
            "method": method_name,
            "payload": _dumps((args, kwargs)),
            "name": name,
            "store_limit": store_limit,
            "num_returns": num_returns,
        }
        if trace_ctx is not None:
            msg["trace_ctx"] = trace_ctx
        reply = self._request(msg)
        return self._unpack(reply, name)

    def destroy_actor(self, actor_id) -> None:
        self._fire_and_forget({"type": "destroy_actor",
                               "actor_id": actor_id.hex()})

    def get_stats(self, timeout: Optional[float] = 10.0) -> dict:
        """Daemon-side counters (object-transfer bytes, actor count)."""
        reply = self._request({"type": "stats"}, timeout=timeout)
        return _loads(reply["value"])

    def profile(self, duration: float = 5.0, hz: int = 100,
                fmt: str = "folded", pid: Optional[int] = None):
        """Ask the daemon to sample ITS OWN stacks (cooperative remote
        profiling; reference: dashboard profile endpoints). ``pid``
        retargets the burst at one of the daemon's pool workers — the
        daemon relays a profile request over that worker's pipe."""
        msg = {"type": "profile", "duration": duration, "hz": hz,
               "fmt": fmt}
        if pid is not None:
            msg["pid"] = int(pid)
        reply = self._request(msg, timeout=duration + 30)
        return _loads(reply["value"])


def describe_value(value) -> str:
    """'<type> of length <n>' for num_returns-mismatch errors — one
    wording shared by the daemon and head reporters."""
    return (f"{type(value).__name__} of length "
            f"{len(value) if hasattr(value, '__len__') else 'n/a'}")


class MismatchedReturn:
    """Marker for a num_returns>1 task whose oversized result had the
    wrong shape: the daemon describes the value instead of storing a
    stub nobody could ever consume (and that would leak in its table)
    or shipping gigabytes to the head just to format an error."""

    __slots__ = ("desc",)

    def __init__(self, desc: str):
        self.desc = desc


class RemoteValueStub:
    """Head-side handle to a result the daemon kept locally (it exceeded
    remote_object_inline_limit_bytes): the ObjectStore materializes it on
    first get via fetch(). Never pickled."""

    __slots__ = ("conn", "key", "size")

    def __init__(self, conn: "NodeConnection", key: str, size: int):
        self.conn = conn
        self.key = key
        self.size = size

    def fetch(self, timeout=None):
        from ray_tpu.exceptions import ObjectLostError
        try:
            return _loads(self.conn.fetch_object(self.key, timeout=timeout))
        except RemoteNodeDiedError as exc:
            raise ObjectLostError(
                f"Object payload {self.key} was on node "
                f"{self.conn.address}, which died before it was fetched "
                "(reconstruction, if possible, re-seals the object)."
            ) from exc


class RemoteArgMarker:
    """Locality marker: an argument whose payload already lives in the
    target daemon's object table travels as this tiny stub and is resolved
    daemon-side — the task-arg analog of a plasma-local read."""

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key


class RemoteActorInstance:
    """Placeholder stored as ActorState.instance for daemon-resident
    actors; method lookups return wire-call closures."""

    def __init__(self, conn: NodeConnection, actor_id):
        self.conn = conn
        self.actor_id = actor_id

    def bind_method(self, method_name: str, task_name: str,
                    store_limit: int = 0, num_returns: int = 1):
        def call(*args, **kwargs):
            # The closure runs INSIDE the head-side actor_task:: span
            # (_run_actor_task's continue_context): propagate THAT span
            # so the daemon-side span parents to it across the wire.
            # span_context (not inject_context) — an untraced call must
            # not mint a new root at this internal layer.
            from ray_tpu.util import tracing
            return self.conn.call_actor_method(
                self.actor_id, method_name, task_name, args, kwargs,
                store_limit, num_returns=num_returns,
                trace_ctx=tracing.span_context(tracing.current_span()))
        return call


class HeadServer:
    """Listens for node-daemon registrations (the GCS node-manager
    surface: register → add_node; disconnect → remove_node)."""

    def __init__(self, runtime, host: str = "0.0.0.0", port: int = 0):
        self.runtime = runtime
        self._listener = socket.create_server((host, port))
        self.address = self._listener.getsockname()[:2]
        self._threads = []
        self._conns: Dict[Any, NodeConnection] = {}
        self._client_sessions: list = []
        self._closed = False
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ray_tpu-head-server",
            daemon=True)
        # Liveness (reference: gcs_health_check_manager.h, upgraded to
        # accrual suspicion + a hard lease — _private/membership.py):
        # EOF catches a dead process; the per-period health probe plus
        # free channel-frame evidence feed each node's phi score, so a
        # hung daemon crosses the suspicion threshold (or the lease)
        # instead of a fixed miss count. A broken session channel sets
        # _probe_wake for an immediate probe (sub-second SIGKILL
        # detection at the 0.25s default period).
        cfg = runtime.config
        self._probe_period = float(cfg.health_probe_period_s)
        self._probe_timeout = float(cfg.health_probe_timeout_s)
        self._lease_s = float(cfg.node_lease_s)
        self._suspicion = float(cfg.node_suspicion_threshold)
        self._probe_wake = threading.Event()
        self._hb_thread = threading.Thread(
            target=self._membership_loop, name="ray_tpu-head-health",
            daemon=True)
        # Cluster-wide usage view fed by daemon pong piggybacks
        # (reference: ray_syncer receiver side in the GCS).
        from ray_tpu._private.syncer import ClusterSyncState
        self.syncer = ClusterSyncState()

    def start(self) -> Tuple[str, int]:
        self._accept_thread.start()
        if self._probe_period > 0:
            self._hb_thread.start()
        return self.address

    def _membership_loop(self) -> None:
        """Suspicion-driven liveness (see _private/membership.py).

        Every ``health_probe_period_s`` (or immediately, when a broken
        session channel sets ``_probe_wake``): fold channel activity
        into each node's accrual detector — frames are free liveness
        evidence, no probe needed for a chatty node — then ping the
        dedicated health socket with ``health_probe_timeout_s``.
        Failures classify HARD (reset/refused while the session channel
        is also broken: the process is gone, declare now) or SOFT
        (timeout or blackholed partition: evidence feeding the phi
        score). Death fires at ``node_suspicion_threshold`` or,
        unconditionally, once silence exceeds ``node_lease_s``."""
        digest_sent: Dict[Any, int] = {}
        from ray_tpu._private.event_stats import GLOBAL
        from ray_tpu._private import builtin_metrics
        import time as _time
        while not self._closed:
            t_wait = _time.monotonic()
            woken = self._probe_wake.wait(self._probe_period)
            self._probe_wake.clear()
            if self._closed:
                return
            if not woken:
                # Head saturation signal: how far past the intended
                # period the sweep actually woke (early wakes excluded —
                # they are on purpose). A busy/GIL-starved head shows up
                # here before anything times out.
                lag = (_time.monotonic() - t_wait) - self._probe_period
                try:
                    builtin_metrics.loop_lag().set(
                        max(0.0, lag), tags={"loop": "head.membership"})
                except Exception:  # noqa: BLE001 - gauge is best-effort
                    pass
            with GLOBAL.timed("head.health_sweep"):
                current = list(self._conns.items())
                # Departed nodes (EOF path) must not leak entries.
                alive_ids = {nid for nid, _ in current}
                for nid in list(digest_sent):
                    if nid not in alive_ids:
                        digest_sent.pop(nid, None)
                # One digest per sweep, shipped to a node only when
                # newer than what it last acked (the only-changed rule
                # the daemon->head direction already follows).
                digest = self.syncer.digest()
                for node_id, conn in current:
                    self._probe_node(node_id, conn, digest, digest_sent)

    def _probe_node(self, node_id, conn: NodeConnection, digest: dict,
                    digest_sent: Dict[Any, int]) -> None:
        import time
        membership = self.runtime.membership
        live = membership.liveness(node_id.hex())
        if live is None:
            return  # already declared dead (racing close)
        # Channel traffic is free liveness: any frame batch the recv
        # loop saw since our last look counts as an arrival — a node
        # mid-transfer (or mid-XLA-compile, pushing metrics_batch
        # heartbeats) never needs its ping answered to stay alive.
        if conn.last_frame_at > live.detector.last_arrival:
            live.record_arrival(conn.last_frame_at)
        hc = conn.health_sock
        hard = soft = None
        if hc is None:
            # Health channel still connecting: no probe possible — only
            # the hard lease bounds how long we wait for it.
            if time.monotonic() - max(conn.registered_at,
                                      live.detector.last_arrival) \
                    > self._lease_s:
                if membership.declare_dead(
                        node_id.hex(), "no health channel within lease"):
                    from ray_tpu._private import builtin_metrics
                    builtin_metrics.node_deaths().inc(
                        tags={"kind": "lease"})
                    logger.warning(
                        "Node %s never opened its health channel within "
                        "the %.1fs lease; declaring it dead",
                        node_id.hex()[:12], self._lease_s)
                    conn.close()
            return
        try:
            # Tiny frames on the dedicated socket: bounded by the socket
            # timeout, never queued behind data transfers and never
            # contending for the data send lock.
            hc.settimeout(self._probe_timeout)
            if _chaos.ACTIVE:
                _chaos.maybe_inject("head.health.send", hc)
            ping: dict = {"type": "ping"}
            if digest["version"] > digest_sent.get(node_id, -1):
                ping["cluster_digest"] = digest
            _send_frame(hc, _dumps(ping))
            if _chaos.ACTIVE:
                _chaos.maybe_inject("head.health.recv", hc)
            pong = _loads(_recv_frame(hc))
            if "cluster_digest" in ping:
                digest_sent[node_id] = digest["version"]
            sync = pong.get("sync")
            if sync:
                self.syncer.apply(node_id.hex(), sync)
            live.record_arrival()
            return
        except (_chaos.ChaosPartition, TimeoutError) as exc:
            # Unreachable, not provably dead: a partition heals, a
            # starved pong thread recovers. Evidence, not a verdict.
            soft = exc
        except (ConnectionError, OSError) as exc:
            hard = exc
        if hard is not None and conn.channel.broken:
            # Session channel broken AND the dedicated health socket
            # actively refused/reset: the process is gone. Declare now
            # instead of burning the reconnect window waiting for a
            # resume that can never come.
            if membership.declare_dead(
                    node_id.hex(), f"process gone: {hard}"):
                from ray_tpu._private import builtin_metrics
                builtin_metrics.node_deaths().inc(tags={"kind": "hard"})
                logger.warning(
                    "Node %s: broken session channel and failed health "
                    "ping (%s); declaring it dead",
                    node_id.hex()[:12], hard)
                conn.close()  # → on_death → remove_node
            return
        live.soft_failures += 1
        now = time.monotonic()
        silent = live.silent_for(now)
        phi = live.phi(now)
        if silent <= self._lease_s and phi < self._suspicion:
            return
        kind = "lease" if silent > self._lease_s else "suspicion"
        if membership.declare_dead(
                node_id.hex(),
                f"{kind}: phi={phi:.1f} silent={silent:.2f}s "
                f"soft_failures={live.soft_failures}"):
            from ray_tpu._private import builtin_metrics
            builtin_metrics.node_deaths().inc(tags={"kind": kind})
            logger.warning(
                "Node %s declared dead (%s: phi=%.1f after %.2fs of "
                "silence, %d failed probes)", node_id.hex()[:12], kind,
                phi, silent, live.soft_failures)
            conn.close()  # → on_death → remove_node

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, addr = self._listener.accept()
            except OSError:
                return
            # Handshake on a short-lived thread with a deadline: one
            # stalled/silent client (port scanner, half-open socket) must
            # not block the accept loop — with the health-channel grace
            # kill, a blocked accept would take down every node whose
            # channel assignment is pending.
            threading.Thread(target=self._handshake, args=(sock, addr),
                             name="ray_tpu-head-handshake",
                             daemon=True).start()

    def _handshake(self, sock: socket.socket, addr) -> None:
        import time as _time

        from ray_tpu._private.event_stats import GLOBAL
        _t0 = _time.monotonic()
        node_id = None
        try:
            sock.settimeout(15)
            register = _loads(_recv_frame(sock))
            sock.settimeout(None)
            if register.get("type") == "client_runtime":
                # A daemon/worker-side user-code process binding a
                # connected runtime (client_runtime.py) — the anti-
                # split-brain surface: nested submits, named actors,
                # refs all resolve against THIS head. Same version
                # handshake as daemons: a client from another release
                # is told exactly why it cannot join.
                try:
                    _wire.check_peer_protocol(
                        register.get("protocol"),
                        f"client runtime at {addr}")
                except _wire.ProtocolMismatch as exc:
                    logger.error("rejecting client runtime: %s", exc)
                    _send_frame_best_effort(sock, _dumps({
                        "type": "register_rejected",
                        "error": str(exc),
                        "head_protocol": _wire.PROTOCOL_VERSION}))
                    sock.close()
                    return
                from ray_tpu._private.client_runtime import ClientSession
                from ray_tpu._private.worker import global_worker as _gw
                session = ClientSession(
                    self.runtime, sock, addr,
                    on_close=self._client_sessions_discard)
                _send_frame(sock, _dumps({
                    "type": "client_registered",
                    "job_id": self.runtime.job_id.hex(),
                    "session_id": self.runtime.session_id,
                    "namespace": _gw.namespace,
                    "head_node_id": self.runtime.head_node_id.hex(),
                    "num_cpus": self.runtime.node_resources.num_cpus,
                    "num_tpus": self.runtime.node_resources.num_tpus,
                }))
                self._client_sessions.append(session)
                threading.Thread(target=session.serve,
                                 name="ray_tpu-client-session",
                                 daemon=True).start()
                GLOBAL.record("head.client_session",
                              _time.monotonic() - _t0)
                return
            if register.get("type") == "resume":
                self._handle_resume(sock, addr, register, _t0)
                return
            if register.get("type") == "health_channel":
                # Second connection from an already-registered daemon,
                # reserved for liveness pings. (Snapshot: recv/health
                # threads pop _conns concurrently.)
                for conn in list(self._conns.values()):
                    if conn.node_id is not None and \
                            conn.node_id.hex() == register["node_id"]:
                        conn.health_sock = sock
                        break
                else:
                    # A declared-dead (or never-known) incarnation's
                    # health thread re-announcing: fence it — counted,
                    # not warned per-announce (a partitioned daemon's
                    # reconnect loop would spam the log).
                    from ray_tpu._private import builtin_metrics, events
                    builtin_metrics.frames_fenced().inc()
                    events.emit(
                        "membership", "fenced unknown health-channel "
                        "announce", severity="warning",
                        node_id=str(register.get("node_id", "")),
                        labels={"kind": "health_channel"})
                    sock.close()
                return
            assert register["type"] == "register", register
            # Version handshake (reference: node_manager.proto contract
            # is compiled in; here it travels explicitly): a daemon
            # from another release is REJECTED with a clear error, not
            # left to fail on some later frame's missing field.
            try:
                _wire.check_peer_protocol(register.get("protocol"),
                                          f"node daemon at {addr}")
            except _wire.ProtocolMismatch as exc:
                logger.error("rejecting daemon registration: %s", exc)
                _send_frame_best_effort(sock, _dumps({
                    "type": "register_rejected",
                    "error": str(exc),
                    "head_protocol": _wire.PROTOCOL_VERSION}))
                sock.close()
                return
            cfg = self.runtime.config
            conn = NodeConnection(
                sock, tuple(addr),
                register["resources"],
                register.get("labels"),
                object_addr=register.get("object_addr"),
                store_name=register.get("store_name"),
                reconnect_window_s=float(cfg.channel_reconnect_window_s),
                resend_ring_bytes=int(cfg.channel_resend_ring_bytes),
                ack_every=int(cfg.channel_ack_every),
                ack_flush_ms=int(cfg.channel_ack_flush_ms))
            conn.rpc_failure_pct = int(
                self.runtime.config.testing_rpc_failure_pct)
            # Registration makes the node schedulable, which can
            # immediately dispatch queued tasks onto this connection
            # from worker threads. The sender is the socket's single
            # writer and its queue is FIFO, so enqueueing the ack
            # BEFORE register_remote_node publishes the conn guarantees
            # "registered" is the first frame the daemon reads — task
            # frames queue behind it. (Pre-r5 this held the send lock
            # instead; the sender thread does not take that lock.)
            node_id = self.runtime.new_node_id()
            conn.node_id = node_id
            # Mint this incarnation's epoch (fenced membership, wire
            # v9) and stamp the channel BEFORE the ack goes out: every
            # enveloped frame of this session carries the epoch, and
            # the ack teaches the daemon its incarnation.
            epoch = self.runtime.membership.mint_epoch(
                node_id.hex(), probe_period_s=self._probe_period or 0.25)
            conn.channel.epoch = epoch
            # session_id rides the ack (additive optional field) so the
            # daemon can join the session's log directory tree.
            conn._sender.send({"type": "registered",
                               "node_id": node_id.hex(),
                               "session_id": self.runtime.session_id,
                               "channel_token": conn.channel_token,
                               "node_epoch": epoch})
            # dispatch=False: the post-ack _dispatch below places
            # queued work once the reply pump is running.
            self.runtime.register_remote_node(
                conn, register, dispatch=False, node_id=node_id)
            conn._on_death = self._on_conn_death
            conn.on_channel_broken = self._probe_wake.set
            self._conns[node_id] = conn
        except Exception:  # noqa: BLE001 - one bad join must not
            # strand a half-registered node.
            if node_id is not None:
                self._conns.pop(node_id, None)
                try:
                    self.runtime.membership.declare_dead(
                        node_id.hex(), "registration failed")
                    self.runtime.unregister_remote_node(node_id)
                except Exception:  # noqa: BLE001
                    logger.exception("rollback of failed node "
                                     "registration failed")
            try:
                sock.close()
            except OSError:
                pass
            GLOBAL.record("head.handshake_failed",
                          _time.monotonic() - _t0)
            return
        t = threading.Thread(target=conn.recv_loop,
                             name=f"ray_tpu-node-{node_id.hex()[:8]}",
                             daemon=True)
        t.start()
        self._threads.append(t)
        # Place queued work on the new node AFTER the send lock is
        # released and the reply pump is running (inline task sends
        # take the send lock; see register_remote_node dispatch=False).
        self.runtime._dispatch()
        GLOBAL.record("head.handshake", _time.monotonic() - _t0)
        logger.info("Node daemon %s joined as %s with %s",
                    addr, node_id.hex()[:12], register["resources"])

    def _handle_resume(self, sock: socket.socket, addr, register: dict,
                       _t0: float) -> None:
        """Re-attach a daemon's broken session channel (wire v7).

        Raw (un-enveloped) handshake: validate protocol + node id +
        channel token, reply ``resumed`` with our last-seen seq, then
        attach the fresh socket — the attach replays every unacked
        frame past the daemon's position. Any rejection sends the
        daemon back to a full re-register, which keeps head-restart
        rebinds (detached actors) as fast as before."""
        import time as _time

        from ray_tpu._private.event_stats import GLOBAL
        try:
            _wire.check_peer_protocol(register.get("protocol"),
                                      f"resuming daemon at {addr}")
        except _wire.ProtocolMismatch as exc:
            _send_frame_best_effort(sock, _dumps({
                "type": "resume_rejected", "error": str(exc)}))
            sock.close()
            return
        epoch = int(register.get("epoch") or 0)
        if epoch and self.runtime.membership.is_fenced(epoch):
            # A declared-dead incarnation back from the far side of a
            # partition: its session (and its actors) died exactly once
            # when the lease expired. The FENCED verdict (vs a generic
            # rejection) tells the daemon to drop its stale residents
            # and re-register as a fresh incarnation.
            from ray_tpu._private import builtin_metrics, events
            builtin_metrics.frames_fenced().inc()
            events.emit(
                "membership",
                f"fenced resume from dead incarnation {epoch}",
                severity="warning",
                node_id=str(register.get("node_id", "")),
                labels={"kind": "resume", "epoch": epoch})
            logger.info(
                "Fencing resume from dead incarnation %d of node %s",
                epoch, str(register.get("node_id"))[:12])
            _send_frame_best_effort(sock, _dumps({
                "type": "fenced", "epoch": epoch,
                "error": "incarnation declared dead; re-register as a "
                         "new node"}))
            sock.close()
            return
        conn = None
        for cand in list(self._conns.values()):
            if cand.node_id is not None and \
                    cand.node_id.hex() == register.get("node_id"):
                conn = cand
                break
        if conn is None or conn._closed or \
                register.get("token") != conn.channel_token:
            _send_frame_best_effort(sock, _dumps({
                "type": "resume_rejected",
                "error": "unknown session (node removed or head "
                         "restarted); re-register"}))
            sock.close()
            return
        # Raw reply BEFORE attach: the daemon reads it to learn our
        # last-seen seq; the replayed (enveloped) frames follow it.
        try:
            _send_frame(sock, _dumps({"type": "resumed",
                                      "last_seq": conn.channel.in_seq}))
        except OSError:
            sock.close()
            return
        if not conn.channel.attach(sock, int(register.get("last_seq", 0))):
            # Resend ring evicted past the daemon's position (or the
            # channel is closed): lossless replay is impossible, so the
            # session is unrecoverable — node death, as before v7.
            conn.close()
            _close_quiet(sock)
            return
        conn.last_frame_at = _monotonic()
        GLOBAL.record("head.channel_resume", _time.monotonic() - _t0)
        logger.info("Node %s resumed its session channel",
                    conn.node_id.hex()[:12] if conn.node_id else addr)

    def _client_sessions_discard(self, session) -> None:
        """Dead client sessions must not accumulate under worker churn."""
        try:
            self._client_sessions.remove(session)
        except ValueError:
            pass

    def _on_conn_death(self, conn: NodeConnection) -> None:
        if self._closed:
            return
        self._conns.pop(conn.node_id, None)
        if conn.node_id is not None:
            self.syncer.remove_node(conn.node_id.hex())
            # EOF/teardown paths reach here without the membership loop:
            # fence the incarnation (exactly-once — a racing probe's
            # declare_dead already returned True and this is a no-op).
            self.runtime.membership.declare_dead(
                conn.node_id.hex(), "connection closed")
        self.runtime.unregister_remote_node(conn.node_id)

    def event_stats(self):
        """Per-handler latency/queue summaries (reference:
        instrumented_io_context.stats() via RAY_event_stats)."""
        from ray_tpu._private.event_stats import GLOBAL
        return GLOBAL.summary()

    def stop(self, keep_nodes=()) -> None:
        """``keep_nodes``: node ids hosting detached actors. Those
        daemons get NO shutdown frame — just a socket close, which their
        run() loop treats as connection loss: resident actors are kept
        for the reconnect window so a restarted head (same port +
        gcs_store_path) can rebind them."""
        self._closed = True
        self._probe_wake.set()  # membership loop exits promptly
        keep = set(keep_nodes or ())
        try:
            self._listener.close()
        except OSError:
            pass
        for node_id, conn in list(self._conns.items()):
            conn._on_death = None  # orderly shutdown, not node death
            if node_id not in keep:
                # Through the sender (the socket's single writer),
                # flushed before close() tears the socket down.
                conn._sender.send({"type": "shutdown", "req_id": 0})
            conn._sender.flush()
            conn.close()
        self._conns.clear()
        # Copy first: session.close() removes itself from the list via
        # the on_close callback — iterating the live list skips entries.
        for session in list(self._client_sessions):
            session.close()
        self._client_sessions.clear()


# ---------------------------------------------------------------------------
# Daemon side
# ---------------------------------------------------------------------------


#: The NodeDaemon serving this process, if any — lets user code running
#: in-daemon (TPU tasks, actor methods) read the gossiped cluster view
#: locally via ray_tpu.cluster_usage() without a round-trip to the head.
_current_daemon: Optional["NodeDaemon"] = None


class _ClassQueue:
    """Daemon-LOCAL dispatch queue for one scheduling class (reference:
    local_task_manager.cc:101 — the raylet owns a per-class queue and
    dispatches to whichever of its leased workers frees up; the head
    only grants capacity). Every lease slot of the class pulls from this
    one FIFO, so the daemon — not the head — decides which worker runs
    which queued task: a slow task no longer head-of-line-blocks the
    work the head happened to pipeline behind it on the same lease.

    Blocked-capacity lending: when the head reports a slot's running
    task blocked in a nested get (spill_lease), that slot's accounted
    capacity was released head-side — the daemon spins up a TEMPORARY
    slot against it (the reference's NotifyDirectCallTaskBlocked
    semantics: a blocked worker's CPU is re-grantable). The temp slot
    retires on unspill. This keeps the deadlock guarantee (a child
    queued behind its blocked parent always finds a slot) without
    draining whole queues onto unbounded threads."""

    def __init__(self, daemon: "NodeDaemon", class_id: str):
        self._daemon = daemon
        self.class_id = class_id
        from collections import deque
        self.dq: Any = deque()
        self.cv = threading.Condition()
        self.slots: set = set()        # live _LeaseExecutor objects
        self.temp_slots = 0            # live temp-slot threads
        self._retire_pending = 0       # unspills waiting to retire one
        self._closed = False           # session over: temp slots exit

    def put(self, item) -> None:
        with self.cv:
            self.dq.append(item)
            self.cv.notify()

    def put_front(self, item) -> None:
        with self.cv:
            self.dq.appendleft(item)
            self.cv.notify()

    def get(self, timeout: float = 0.5):
        with self.cv:
            if not self.dq:
                self.cv.wait(timeout)
            return self.dq.popleft() if self.dq else None

    def pop_tail(self, max_n: int) -> list:
        """Reclaim (head spillback): hand back up to max_n NOT-STARTED
        tasks from the tail — the most recently pipelined, so FIFO
        fairness for the rest is untouched."""
        out = []
        with self.cv:
            while self.dq and len(out) < max_n:
                out.append(self.dq.pop())
        return out

    def qsize(self) -> int:
        return len(self.dq)

    def spill(self) -> None:
        """One slot's task blocked head-side: lend its capacity to a
        temporary slot serving this queue."""
        with self.cv:
            self.temp_slots += 1
        threading.Thread(target=self._temp_loop,
                         name=f"ray_tpu-temp-{self.class_id}",
                         daemon=True).start()

    def unspill(self) -> None:
        """The blocked task resumed: retire one temp slot (after its
        current task, if it grabbed one)."""
        with self.cv:
            self._retire_pending += 1
            self.cv.notify_all()

    def close(self) -> None:
        """Session teardown: every temp slot must exit — the head that
        would have sent the retiring unspill is gone."""
        with self.cv:
            self._closed = True
            self.cv.notify_all()

    def _temp_loop(self) -> None:
        try:
            while True:
                with self.cv:
                    if self._closed:
                        return
                    if self._retire_pending > 0:
                        self._retire_pending -= 1
                        return
                item = self.get(timeout=0.2)
                if item is None:
                    continue
                sock, msg = item
                # No pinned worker: per-task pool lease (temp slots are
                # short-lived; pinning would hoard subprocesses).
                self._daemon._handle_counted(sock, msg)
        finally:
            with self.cv:
                self.temp_slots -= 1

    def drain_to_threads(self) -> None:
        """Last slot retired with work still queued (head/daemon
        accounting drift — should not happen): never strand tasks."""
        while True:
            with self.cv:
                if not self.dq:
                    return
                sock, msg = self.dq.popleft()
            threading.Thread(target=self._daemon._handle_counted,
                             args=(sock, msg), daemon=True).start()


class _LeaseExecutor:
    """Daemon-side half of a worker lease (reference: raylet's leased
    worker + direct_task_transport pipelining): one dedicated thread =
    one accounted resource acquisition. In SHARED mode (CPU classes) the
    thread is a slot on the class's local dispatch queue — the daemon
    decides which slot runs which task (_ClassQueue). In SERIAL mode
    (TPU classes, whose tasks carry chip ids the head accounted to THIS
    lease) it keeps its own strict-FIFO queue, so two tasks holding the
    same chips can never overlap. Worker-process tasks pin ONE
    subprocess for the lease's lifetime (no per-task pool traffic)."""

    def __init__(self, daemon: "NodeDaemon", lease_id: str,
                 cq: Optional[_ClassQueue] = None):
        self._daemon = daemon
        self.lease_id = lease_id
        self._cq = cq
        import queue as _queue
        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._stopping = False
        self.worker_handle = None  # pinned worker subprocess (if any)
        self.worker_python = None
        self.tasks_run = 0
        # SERIAL mode only — set while the lease's running task is
        # blocked in a nested get: tasks that raced onto the wire before
        # the head stopped attaching must bypass the serial queue, or
        # one could land behind the blocked parent it is a dependency
        # of. CLEARED by the head's unspill_lease when the get returns.
        self.spilled = False
        if cq is not None:
            with cq.cv:
                cq.slots.add(self)
        self._thread = threading.Thread(
            target=self._run_shared if cq is not None else self._run,
            name=f"ray_tpu-lease-{lease_id}", daemon=True)
        self._thread.start()

    def submit(self, sock, msg: dict) -> None:
        if self._cq is not None:
            self._cq.put((sock, msg))
        else:
            self._q.put((sock, msg))

    def stop(self) -> None:
        self._stopping = True
        if self._cq is not None:
            with self._cq.cv:
                self._cq.cv.notify_all()
        else:
            self._q.put(None)

    def spill(self) -> None:
        """The lease's running task blocked in a nested get; its
        capacity was lent out head-side. SHARED mode: lend it to a temp
        slot. SERIAL mode: move every waiting task off this serial
        queue onto its own handler thread (concurrency sanctioned by
        the released capacity) — a child pipelined behind its blocked
        parent must never deadlock."""
        if self._cq is not None:
            self._cq.spill()
            return
        self.spilled = True
        import queue as _queue
        while True:
            try:
                item = self._q.get_nowait()
            except _queue.Empty:
                return
            if item is None:
                self._q.put(None)  # re-arm the stop sentinel
                return
            sock, msg = item
            threading.Thread(target=self._daemon._handle_counted,
                             args=(sock, msg), daemon=True).start()

    def unspill(self) -> None:
        """Resume normal capacity (the head cleared lease.blocked)."""
        if self._cq is not None:
            self._cq.unspill()
            return
        self.spilled = False

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                break
            sock, msg = item
            msg["_lease_exec"] = self  # daemon-local pin context
            self.tasks_run += 1
            self._daemon._handle_counted(sock, msg)
        self._release_pinned()

    def _run_shared(self) -> None:
        cq = self._cq
        try:
            while True:
                item = cq.get(timeout=0.5)
                if self._stopping:
                    if item is not None:
                        cq.put_front(item)  # another slot takes it
                    break
                if item is None:
                    continue
                sock, msg = item
                msg["_lease_exec"] = self  # daemon-local pin context
                self.tasks_run += 1
                self._daemon._handle_counted(sock, msg)
        finally:
            with cq.cv:
                cq.slots.discard(self)
                last = not cq.slots
            if last and cq.qsize():
                cq.drain_to_threads()
            self._release_pinned()

    def _release_pinned(self) -> None:
        handle = self.worker_handle
        self.worker_handle = None
        if handle is not None:
            try:
                self._daemon._get_pool().release(handle)
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


def _reap_stale_spill_dirs(parent: str) -> None:
    """Remove ray_tpu_spill_<pid> dirs whose owning process is dead
    (reference: the raylet reclaims its spill directory on restart)."""
    import shutil
    try:
        entries = os.listdir(parent)
    except OSError:
        return
    for fname in entries:
        if not fname.startswith("ray_tpu_spill_"):
            continue
        try:
            pid = int(fname.rsplit("_", 1)[1])
        except ValueError:
            continue
        if pid == os.getpid() or procinfo.pid_alive(pid):
            continue
        shutil.rmtree(os.path.join(parent, fname), ignore_errors=True)


class NodeDaemon:
    """The per-node daemon (raylet + worker-pool analog): executes pushed
    CPU tasks in real worker subprocesses (crash isolation — a dying
    task kills one worker, not the node), runs TPU tasks in-process (the
    chip is single-process), hosts actor instances. Owns the node's
    object table (shm arena, shared with its workers) + object server —
    the distributed data plane's local half (_private/dataplane.py)."""

    def __init__(self, head_address: Tuple[str, int],
                 resources: Dict[str, float],
                 labels: Optional[dict] = None,
                 object_store_memory: int = 1 << 28,
                 spill_dir: Optional[str] = None):
        self.head_address = head_address
        self.resources = resources
        self.labels = labels or {}
        self._functions: Dict[bytes, Any] = {}
        # Raw fn_bytes cached by the single recv-loop thread BEFORE the
        # request is handed to a handler thread. The head ships bytes only
        # on first use; a concurrent second request (fn_bytes=None) could
        # otherwise race the first handler's load and fail spuriously.
        self._fn_raw: Dict[bytes, bytes] = {}
        self._actors: Dict[str, Any] = {}
        self._actor_tpu_ids: Dict[str, Any] = {}
        # Node object table (local half of the data plane): big results
        # stay here — in the shm arena when available — until freed;
        # peer daemons pull them directly over the object server (which
        # binds lazily in run(), on the head-facing interface).
        from ray_tpu._private import dataplane
        from ray_tpu._private.dataplane import (NodeObjectTable,
                                                PullAdmission)
        from ray_tpu._private.ray_config import make_ray_config
        _cfg = make_ray_config(None)
        # Pull tuning travels through RayConfig so the flag pipeline
        # (env > system config > defaults) governs the data plane too.
        dataplane.configure_pulls(int(_cfg.pull_chunk_bytes),
                                  int(_cfg.pull_parallelism))
        # Disk spill keeps memory pressure from ever LOSING a block
        # (reference: raylet spill/restore, local_object_manager.h).
        # Directory precedence: explicit arg > the object_spilling_
        # directory config flag (the same one the head store honors —
        # a user pointing spill at NVMe scratch gets BOTH stores there)
        # > a per-daemon dir under the system temp dir.
        if spill_dir is None:
            spill_dir = _cfg.object_spilling_directory or None
        if spill_dir is None:
            import tempfile
            spill_dir = os.path.join(
                tempfile.gettempdir(),
                f"ray_tpu_spill_{os.getpid()}")
        else:
            spill_dir = os.path.join(
                spill_dir, f"ray_tpu_spill_{os.getpid()}")
        self._spill_dir = spill_dir
        # Crashed daemons (SIGKILL/OOM) never run close(): reap sibling
        # ray_tpu_spill_<pid> dirs AND /dev/shm arenas whose pid is
        # gone, in the background (a dead shuffle can leave tens of GB
        # behind in each).
        def _reap(parent=os.path.dirname(spill_dir)):
            _reap_stale_spill_dirs(parent)
            from ray_tpu._private.native_store import reap_stale_arenas
            reap_stale_arenas()

        threading.Thread(target=_reap,
                         name="ray_tpu-spill-reaper", daemon=True).start()
        self._table = NodeObjectTable(capacity=object_store_memory,
                                      spill_dir=spill_dir)
        # Durable spill tier (reference: local_object_manager.h external
        # storage): a configured spill URI swaps the table's backend so
        # spilled payloads survive this daemon's death. session:// needs
        # the head's session id — upgraded at registration; other
        # schemes (file://, mock-s3://, registered remotes) bind now.
        self._spill_uri = str(_cfg.object_spill_uri or "")
        if self._spill_uri and \
                not self._spill_uri.startswith("session://"):
            from ray_tpu._private.spill import backend_for_uri
            try:
                self._table.set_spill_backend(backend_for_uri(
                    self._spill_uri, fallback_dir=spill_dir))
            except ValueError:
                logger.exception("invalid object_spill_uri %r; keeping "
                                 "the local spill directory",
                                 self._spill_uri)
        # Durable-spill announcements ride the session's reply sender;
        # the head records URIs in its object location table.
        self._table.on_spilled = self._announce_spilled
        self._table.on_unspilled = self._announce_unspilled
        # Pull admission control (reference: pull_manager.h:52): bounds
        # bytes in flight into this node, task args first.
        self._table.admission = PullAdmission(
            int(_cfg.pull_manager_max_inflight_bytes))
        self._object_server = None
        import uuid as _uuid
        self._uid = _uuid.uuid4().hex[:8]
        # Incremented per head session (reconnects): result keys embed it
        # so a stale handler's late put can never overwrite an object a
        # NEW session stored under the same (restarted) req_id.
        self._session_n = 0
        self._send_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        # The session's ResilientChannel (survives resume socket
        # swaps); handlers and publish paths key on it, not the socket.
        self._chan = None
        # Per-session reply sender (channel -> _CoalescingSender): the
        # single writer for head-bound replies; completions accumulated
        # by concurrent handler threads coalesce into reply_batch
        # frames. Handlers of a DEAD session find no sender and fall
        # back to a direct send into the closed channel (dropped).
        self._reply_senders: Dict[Any, Any] = {}
        self._stop = threading.Event()
        self.node_id_hex: Optional[str] = None
        # Incarnation epoch from the registration ack (wire v9): stamps
        # every enveloped frame; carried by resume ("am I still this
        # incarnation?") and by the next register as prev_epoch (so a
        # head that fenced us can sweep any stale residue).
        self._node_epoch = 0
        # Worker-process pool (reference: raylet WorkerPool): CPU tasks
        # run in real worker subprocesses by default — crash isolation
        # for the node; a segfaulting task kills one worker, not the
        # daemon. TPU tasks stay in-daemon (the chip is single-process).
        import os as _os
        self._use_worker_processes = _os.environ.get(
            "RAY_TPU_DAEMON_WORKER_PROCESSES", "1") != "0"
        self._pool = None
        self._pool_lock = threading.Lock()
        self._prefetch_pool = None  # lazy; parallel task-arg pulls
        self._prestarted = False
        self._session_registered = False
        self._health_started = False
        # Started once per daemon on the first registration that hands
        # us a session id (like _health_started): tails this process's
        # capture files — its own raylet streams + spawned workers —
        # and ships batches head-ward.
        self._log_monitor = None
        # Interval exporter for this daemon's metric registry (plus the
        # batches its leased workers piggyback on task replies); ships
        # metrics_batch frames through the session's reply sender.
        self._metrics_agent = None
        self._object_server_host: Optional[str] = None
        # Resource-usage sync (reference: common/ray_syncer): changed
        # component snapshots piggyback on health-channel pongs; the
        # head's aggregated cluster digest rides back on pings.
        from ray_tpu._private.syncer import (DigestCache,
                                             NodeSyncReporter)
        self.syncer_reporter = NodeSyncReporter()
        self.cluster_digest = DigestCache()
        self._inflight = 0
        self._inflight_cpu = 0.0
        self._inflight_lock = threading.Lock()
        # Daemon-local dispatch queues: class_id -> _ClassQueue (the
        # node's own task queues; see _ClassQueue docstring). Recv-loop
        # writes, slot threads read.
        self._class_queues: Dict[str, _ClassQueue] = {}
        # Live worker leases: lease_id -> _LeaseExecutor (recv-loop only).
        self._lease_executors: Dict[str, _LeaseExecutor] = {}
        self._lease_tasks_total = 0
        self._register_sync_collectors()

    def _register_sync_collectors(self) -> None:
        from ray_tpu._private import syncer as _sync

        def resource_load():
            with self._inflight_lock:
                inflight = self._inflight
                cpu_used = self._inflight_cpu
            avail = dict(self.resources)
            if "CPU" in avail:
                avail["CPU"] = max(0.0, avail["CPU"] - cpu_used)
            return {"total": dict(self.resources), "available": avail,
                    "inflight_tasks": inflight,
                    "actors": len(self._actors)}

        def object_store():
            return self._table.usage()

        def memory():
            try:
                with open("/proc/self/status") as f:
                    for line in f:
                        if line.startswith("VmRSS:"):
                            kb = int(line.split()[1])
                            return {"rss_bytes": kb * 1024}
            except OSError:
                pass
            return None

        def backlog():
            # Local dispatch state: per-class queue depth + lent-out
            # temp slots. The head reads this through the syncer for
            # spillback decisions and the state API — it does NOT see
            # the queues directly (they are daemon-owned).
            classes = {cid: cq.qsize()
                       for cid, cq in list(self._class_queues.items())}
            return {"classes": classes,
                    "queued": sum(classes.values()),
                    "temp_slots": sum(
                        cq.temp_slots
                        for cq in list(self._class_queues.values()))}

        self.syncer_reporter.register(_sync.RESOURCE_LOAD, resource_load)
        self.syncer_reporter.register(_sync.OBJECT_STORE, object_store)
        self.syncer_reporter.register(_sync.MEMORY, memory)
        self.syncer_reporter.register(_sync.BACKLOG, backlog)

    def _reclaim_tasks(self, sock, msg: dict) -> None:
        """Head spillback (reference: cluster_task_manager.cc spillback):
        hand back up to max_n queued-not-started tasks of a class so the
        head can re-dispatch them onto capacity that freed elsewhere.
        Each reclaimed task's req_id answers {"reclaimed": True} — the
        head's normal completion path re-routes it."""
        cq = self._class_queues.get(msg.get("class_id"))
        popped = (cq.pop_tail(int(msg.get("max_n", 0)))
                  if cq is not None else [])
        for psock, pmsg in popped:
            self._send_reply(psock, {"req_id": pmsg.get("req_id", 0),
                                     "ok": True, "reclaimed": True})
        if msg.get("req_id"):
            self._reply(sock, msg["req_id"], value=len(popped))

    def _load_function(self, fn_id: bytes, fn_bytes: Optional[bytes]):
        fn = self._functions.get(fn_id)
        if fn is None:
            from ray_tpu._private import serialization
            if fn_bytes is None:
                # The recv loop cached the raw bytes from the first frame
                # that shipped them (frames are ordered on one socket, so
                # by the time a fn_bytes=None request is READ, the cache
                # is already populated).
                fn_bytes = self._fn_raw.get(fn_id)
            if fn_bytes is None:
                raise RuntimeError("head sent no bytes for unknown function")
            fn = serialization.loads_function(fn_bytes)
            self._functions[fn_id] = fn
            # _fn_raw keeps the raw bytes too: every NEW worker process
            # needs them shipped once (the reference likewise retains
            # function exports in GCS KV for the job's lifetime).
        return fn

    def _send_reply(self, session, msg: dict, nbytes: int = 0) -> None:
        """Route a reply through the session's coalescing sender (the
        channel's single writer). Handlers that outlive their session
        find no sender and fall back to a direct send into the closed
        channel — which raises and gets dropped, the intent (see
        _reply's docstring on head restarts). ``session`` is the
        ResilientChannel the request arrived on (a raw socket for
        legacy callers)."""
        sender = self._reply_senders.get(session)
        if sender is not None and sender.send(msg, nbytes=nbytes):
            return
        if isinstance(msg.get("value"), (list, tuple)):
            # OOB part-list values only flow through the typed encoder;
            # the raw fallback pickles the dict, so join first.
            msg = dict(msg, value=_join_parts(list(msg["value"])))
        if hasattr(session, "send_frame"):
            session.send_frame(_dumps(msg))
        else:
            _send_frame(session, _dumps(msg), self._send_lock)

    def _reply(self, sock, req_id: int, *, value: Any = None,
               error: Optional[BaseException] = None,
               tb: str = "") -> None:
        """``sock`` is the session socket the REQUEST arrived on. After a
        head restart, handler threads of the dead session still hold the
        old (closed) socket — their replies raise OSError and are
        dropped instead of reaching the new head with req_ids that
        collide with the new session's counter (the restarted head
        re-runs those tasks anyway)."""
        if error is not None:
            try:
                payload = _dumps((error, tb))
            except Exception:  # noqa: BLE001 - unpicklable exception
                payload = _dumps((RuntimeError(
                    f"{type(error).__name__}: {error}"), tb))
            msg = {"req_id": req_id, "ok": False, "error": payload}
            self._send_reply(sock, msg, nbytes=len(payload))
            return
        payload = _dumps(value)
        self._send_reply(sock, {"req_id": req_id, "ok": True,
                                "value": payload}, nbytes=len(payload))

    def _reply_result(self, sock, req_id: int, result: Any,
                      store_limit: int, num_returns: int = 1) -> None:
        """Small results return inline (the reference's PushTaskReply
        path); big ones stay in this daemon's object table and only a
        (key, size) stub travels back. Multi-return tasks split PER
        ELEMENT — each return object is independently inline or
        daemon-resident, so shuffle partials never transit the head."""
        if num_returns > 1 and (not isinstance(result, (tuple, list))
                                or len(result) != num_returns):
            # Wrong shape for a multi-return task: the head will raise —
            # describe the actual value here (it is already deserialized)
            # rather than parking an unconsumable stub in the table.
            self._send_reply(sock, {
                "req_id": req_id, "ok": True,
                "mismatch_desc": describe_value(result)})
            return
        if num_returns > 1 and store_limit and \
                isinstance(result, (tuple, list)) and \
                len(result) == num_returns:
            element_parts = [_dumps_parts(element) for element in result]
            sizes = [_parts_size(pp) for pp in element_parts]
            if sum(sizes) > store_limit:
                parts = []
                for i, (pp, size) in enumerate(zip(element_parts, sizes)):
                    if size > store_limit:
                        key = (f"obj-{self._uid}-s{self._session_n}-"
                               f"{req_id}-r{i}")
                        self._table.put_parts(key, pp, size=size)
                        parts.append({"stored_key": key,
                                      "size": size})
                    else:
                        parts.append({"value": _join_parts(pp)})
                self._send_reply(
                    sock, {"req_id": req_id, "ok": True, "parts": parts},
                    nbytes=sum(len(p.get("value") or b"")
                               for p in parts))
                return
            # Small total: the plain inline reply below is cheaper than
            # per-element bookkeeping head-side.
        result_parts = _dumps_parts(result)
        size = _parts_size(result_parts)
        if store_limit and size > store_limit:
            # Globally unique key: peer daemons cache pulled copies under
            # the same name, so it must not collide across nodes.
            key = f"obj-{self._uid}-s{self._session_n}-{req_id}"
            self._table.put_parts(key, result_parts, size=size)
            self._send_reply(sock, {"req_id": req_id, "ok": True,
                                    "stored_key": key,
                                    "size": size})
        else:
            # Part list straight through: the typed reply encoder hands
            # the pickle-5 OOB buffers to send_parts unjoined.
            self._send_reply(sock, {"req_id": req_id, "ok": True,
                                    "value": result_parts},
                             nbytes=size)

    def _pull_marker(self, a) -> None:
        """Land a marker argument's payload in the local table: direct
        peer pull with holder failover (the marker's alt_addrs are the
        head's other known in-memory holders), then the durable spill
        URI as the last data-plane resort — only when every tier misses
        does the caller's error escalate into lineage reconstruction."""
        from ray_tpu._private.dataplane import (PULL_PRIORITY_TASK_ARGS,
                                                ObjectPullError,
                                                pull_object)
        owner = getattr(a, "owner_addr", None)
        spill_uri = getattr(a, "spill_uri", None)
        try:
            if owner is None:
                raise KeyError(
                    f"object payload {a.key} is not resident on "
                    "this node (already freed?)")
            # Direct peer pull — the head never sees these bytes
            # (reference: ObjectManager node-to-node chunked pull).
            pull_object(tuple(owner), a.key, self._table,
                        priority=PULL_PRIORITY_TASK_ARGS,
                        size_hint=getattr(a, "size", 0) or 0,
                        fallback_addrs=getattr(a, "alt_addrs", ()) or ())
            return
        except (ObjectPullError, KeyError, OSError) as exc:
            if not spill_uri:
                raise
            import time as _time
            from ray_tpu._private.spill import read_uri
            t0 = _time.monotonic()
            payload = read_uri(spill_uri,
                               getattr(a, "size", 0) or 0)
            if payload is None:
                raise ObjectPullError(
                    f"object {a.key}: every holder failed ({exc}) and "
                    f"its spill URI {spill_uri} is unreadable") from exc
            logger.warning("restored %s from spill URI %s after holder "
                           "failure: %s", a.key, spill_uri, exc)
            self._table.put(a.key, payload)
            try:
                from ray_tpu._private import builtin_metrics, flow
                builtin_metrics.object_restores().inc(
                    tags={"source": "spill"})
                # Spill restores are transfers too: the ledger entry
                # carries tier="spill" and a synthetic "spill" peer, so
                # the head's matrix shows restore bandwidth per node.
                flow.global_flow_recorder().record(
                    key=a.key, nbytes=len(payload),
                    duration_s=_time.monotonic() - t0,
                    direction="in", peer="spill", tier="spill")
            except Exception:  # noqa: BLE001 - accounting only
                pass

    def _handle_push_object(self, msg: dict) -> dict:
        """One spanning-tree broadcast edge landing on this node. Either
        the payload rides inline (``data``: head seeding a direct child)
        or this node blocking-waits on its ``parent``'s object server
        until the parent's own copy arrives, then pulls node-to-node.
        A dead parent re-parents through ``alts`` (grandparent, then
        root), so one SIGKILL orphans a subtree for exactly one failover
        instead of killing the broadcast."""
        import time as _time

        from ray_tpu._private import flow
        from ray_tpu._private.dataplane import (PULL_PRIORITY_TASK_ARGS,
                                                ObjectPullError, pull_object,
                                                wait_remote)
        key = msg["key"]
        if self._table.stat(key) >= 0:
            return {"bytes": 0, "failovers": 0, "secs": 0.0,
                    "already": True}
        data = msg.get("data")
        if data is not None:
            t0 = _time.monotonic()
            self._table.put(key, data)
            secs = _time.monotonic() - t0
            try:
                # Head-seeded edges are the only ones that cost head
                # egress: the synthetic "head" peer makes them a
                # distinct row in the flow matrix.
                flow.global_flow_recorder().record(
                    key=key, nbytes=len(data), duration_s=secs,
                    direction="in", peer="head", tier="push")
            except Exception:  # noqa: BLE001 - accounting only
                pass
            return {"bytes": len(data), "failovers": 0, "secs": secs}
        wait_s = float(msg.get("wait_timeout_s", 60.0))
        candidates = []
        if msg.get("parent"):
            candidates.append(tuple(msg["parent"]))
        candidates.extend(tuple(a) for a in msg.get("alts", ()))
        last_exc: Optional[BaseException] = None
        for i, cand in enumerate(candidates):
            try:
                got = wait_remote(cand, key, timeout=wait_s)
                if got < 0:
                    raise ObjectPullError(
                        f"object {key} never landed on parent "
                        f"{cand[0]}:{cand[1]} within {wait_s:.0f}s")
                t0 = _time.monotonic()
                pull_object(cand, key, self._table,
                            priority=PULL_PRIORITY_TASK_ARGS,
                            size_hint=got,
                            fallback_addrs=candidates[i + 1:],
                            tier="push")
                return {"bytes": got, "failovers": i,
                        "secs": _time.monotonic() - t0}
            except (ObjectPullError, OSError, ConnectionError) as exc:
                last_exc = exc
        raise ObjectPullError(
            f"broadcast push of {key} failed: no parent in "
            f"{candidates!r} produced the object") from last_exc

    def _resolve_markers(self, args, kwargs):
        from ray_tpu._private.dataplane import (ObjectMarker,
                                                ObjectPullError)
        self._prefetch_marker_args(args, kwargs)

        def resolve(a):
            if isinstance(a, (ObjectMarker, RemoteArgMarker)):
                with self._table.pinned(a.key) as payload:
                    if payload is not None:
                        return _loads(payload)
                self._pull_marker(a)
                with self._table.pinned(a.key) as payload:
                    if payload is None:  # evicted immediately (pressure)
                        raise ObjectPullError(
                            f"object {a.key} was evicted right after its "
                            "pull (object store too small?)")
                    return _loads(payload)
            return a
        return ([resolve(a) for a in args],
                {k: resolve(v) for k, v in kwargs.items()})

    def _get_pool(self):
        with self._pool_lock:
            if self._pool is None:
                from ray_tpu._private.worker_process import WorkerProcessPool
                # head_address: workers bind a ClientRuntime for nested
                # ray_tpu API calls (see _private/client_runtime.py).
                object_addr = None
                if self._object_server is not None and \
                        self._object_server_host:
                    object_addr = (self._object_server_host,
                                   self._object_server.port)
                self._pool = WorkerProcessPool(
                    store_name=self._table.arena_name,
                    head_address=self.head_address,
                    node_id_hex=self.node_id_hex,
                    object_addr=object_addr)
                # Worker metric batches hop worker -> this daemon ->
                # head, keeping the worker's own pid/component labels.
                self._pool.metrics_sink = self._publish_metrics_batch
                self._pool.profile_sink = self._publish_profile_batch
                self._pool.flow_sink = self._publish_flow_batch
            return self._pool

    def _task_uses_worker_process(self, msg: dict) -> bool:
        if msg.get("tpu_ids"):
            return False  # the daemon owns the chips; stay in-process
        renv = msg.get("runtime_env") or {}
        if renv.get("worker_process") is False:
            return False
        return self._use_worker_processes or bool(
            renv.get("worker_process") or renv.get("pip")
            or renv.get("venv") or renv.get("conda")
            or renv.get("container"))

    def _prefetch_marker_args(self, args, kwargs) -> None:
        """Pull a task's missing peer-owned argument payloads in
        PARALLEL before the sequential resolve walk (reference:
        pull_manager batches a task's arg pulls; one-at-a-time pulls
        made a 32-arg reduce task pay 32 serial round-trips). Errors
        are swallowed here — resolve() re-pulls the stragglers and
        raises with full context."""
        from ray_tpu._private.dataplane import (PULL_PRIORITY_TASK_ARGS,
                                                ObjectMarker, pull_object)
        missing = {}
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (ObjectMarker, RemoteArgMarker)):
                owner = getattr(a, "owner_addr", None)
                if owner is not None and a.key not in missing and \
                        not self._table.contains(a.key):
                    missing[a.key] = (tuple(owner),
                                      getattr(a, "size", 0) or 0,
                                      getattr(a, "alt_addrs", ()) or ())
        if len(missing) < 2:
            return  # a single pull gains nothing from the pool
        pool = self._prefetch_pool
        if pool is None:
            import concurrent.futures as _cf
            with self._pool_lock:
                pool = self._prefetch_pool
                if pool is None:
                    # PERSISTENT: a per-task executor would pay thread
                    # spawn/join on every multi-arg dispatch.
                    pool = _cf.ThreadPoolExecutor(
                        8, thread_name_prefix="ray_tpu-prefetch")
                    self._prefetch_pool = pool
        futures = [
            pool.submit(pull_object, owner, key, self._table,
                        priority=PULL_PRIORITY_TASK_ARGS, size_hint=size,
                        fallback_addrs=alts)
            for key, (owner, size, alts) in missing.items()]
        for f in futures:
            f.exception()  # wait; failures re-raise in resolve()

    def _resolve_markers_for_worker(self, args, kwargs):
        """Like _resolve_markers, but arena-resident payloads stay as
        ArenaRef markers: the worker attaches the same shm arena and
        reads them zero-copy (no daemon→worker copy of big args).

        Every ArenaRef'd key is PINNED (arena refcount) for the dispatch;
        the returned pin list must be released when the worker is done.
        Without the pin, disk spill could evict the entry between this
        resolve and the worker's read (plasma semantics: an argument of
        a dispatched task holds a reference, local_task_manager.cc pins
        args for the task's runtime)."""
        from ray_tpu._private.dataplane import (ObjectMarker,
                                                ObjectPullError)
        from ray_tpu._private.worker_process import ArenaRef
        self._prefetch_marker_args(args, kwargs)
        pinned: list = []

        def _pin_in_arena(arena, key) -> bool:
            view = arena.get_bytes(key)
            if view is None:
                return False
            try:
                view.release()
            except BufferError:
                pass
            pinned.append(key)  # arena refcount held until release_pins
            return True

        def resolve(a):
            if isinstance(a, (ObjectMarker, RemoteArgMarker)):
                if not self._table.contains(a.key):
                    self._pull_marker(a)
                arena = self._table._arena
                if arena is not None:
                    if _pin_in_arena(arena, a.key):
                        return ArenaRef(a.key)
                    # Spilled? A read restores+promotes it; retry the pin
                    # so the worker still gets the zero-copy path. If
                    # promotion failed (arena still full) use the bytes
                    # we already read — never a second full disk read on
                    # a node that is under memory pressure.
                    if self._table._spill_dir is not None:
                        data = self._table._read_spilled(a.key)
                        if data is not None:
                            if _pin_in_arena(arena, a.key):
                                return ArenaRef(a.key)
                            return _loads(data)
                with self._table.pinned(a.key) as payload:
                    if payload is None:
                        raise ObjectPullError(
                            f"object {a.key} evicted right after pull")
                    return _loads(payload)
            return a
        try:
            return ([resolve(a) for a in args],
                    {k: resolve(v) for k, v in kwargs.items()}, pinned)
        except BaseException:
            self._release_arena_pins(pinned)
            raise

    def _release_arena_pins(self, pinned) -> None:
        arena = self._table._arena
        if arena is None:
            return
        for key in pinned:
            try:
                arena.release(key)
            except Exception:  # noqa: BLE001 - release is best-effort
                pass

    def _execute_on_worker(self, sock, msg: dict, req_id: int) -> None:
        """Run a pushed task on a leased worker subprocess and forward
        its (already serialized) result without re-encoding."""
        from ray_tpu._private.runtime_env_pip import python_for_env
        from ray_tpu._private.worker_process import (WorkerCrashedError,
                                                     WorkerFnMissingError)
        pool = self._get_pool()
        renv = msg.get("runtime_env") or {}
        python = python_for_env(renv)
        container = renv.get("container")
        lease_ex = msg.get("_lease_exec")
        if lease_ex is not None and not container:
            # Leased task: the lease pins ONE worker subprocess for its
            # whole lifetime (reference: a granted lease IS a worker).
            # Containerized tasks always pool-lease (the pool keys by
            # image; pinning would mix images on one lease).
            handle = lease_ex.worker_handle
            if handle is None or handle.dead or \
                    lease_ex.worker_python != python:
                if handle is not None:
                    pool.release(handle)
                handle = pool.lease(python)
                lease_ex.worker_handle = handle
                lease_ex.worker_python = python
        else:
            handle = pool.lease(python, container=container)
            lease_ex = None  # containerized: never pin
        arg_pins: list = []
        try:
            if msg.get("plain_args"):
                # Head vouched the payload holds no markers: forward the
                # bytes to the worker untouched (no unpickle→repickle).
                args_payload = msg["payload"]
            else:
                with _trace_span(msg.get("trace_ctx"),
                                 "data::resolve_args", "pull"):
                    args, kwargs, arg_pins = \
                        self._resolve_markers_for_worker(
                            *_loads(msg["payload"]))
                args_payload = _dumps((args, kwargs))
            fn_id = msg["fn_id"]

            # Big results write straight into the shared arena
            # worker-side (no stdio pipe copy); the daemon adopts the
            # entries below. Multi-returns split per element in the
            # worker (a shuffle map's partitions each land separately).
            arena_limit = 0
            if self._table.arena_name is not None:
                arena_limit = int(msg.get("store_limit", 0) or 0)

            def build(fn_bytes):
                renv = {k: v for k, v in (msg.get("runtime_env")
                                          or {}).items()
                        if k != "worker_process"}
                return {
                    "type": "exec",
                    "mode": "task",
                    "fn_id": fn_id,
                    "fn_bytes": fn_bytes,
                    "payload": args_payload,
                    "runtime_env": renv,
                    "name": msg.get("name", "task"),
                    "task_id": msg.get("task_id"),
                    "arena_limit": arena_limit,
                    "num_returns": msg.get("num_returns", 1),
                    # Second hop of the propagation: the worker
                    # subprocess parents its execute span to the same
                    # driver-side context.
                    "trace_ctx": msg.get("trace_ctx"),
                }

            def fn_payload():
                fb = msg.get("fn_bytes") or self._fn_raw.get(fn_id)
                if fb is None:
                    raise RuntimeError(
                        "no function bytes available for worker dispatch")
                return fb

            if fn_id in handle.shipped:
                reply = handle.request(build(None))
                if not reply.get("ok"):
                    exc, _tb = _loads(reply["error"])
                    if isinstance(exc, WorkerFnMissingError):
                        # Shipped-set out of sync (a prior request died
                        # before the worker cached the fn): heal once.
                        handle.shipped.discard(fn_id)
                        reply = handle.request(build(fn_payload()))
                        handle.shipped.add(fn_id)
            else:
                reply = handle.request(build(fn_payload()))
                handle.shipped.add(fn_id)
        except WorkerCrashedError as exc:
            # Ships to the head as TaskError(cause=WorkerCrashedError),
            # which the head classifies as system-retriable.
            self._reply(sock, req_id, error=exc, tb=traceback.format_exc())
            return
        finally:
            self._release_arena_pins(arg_pins)
            if lease_ex is not None:
                if handle.dead:  # crashed: un-pin; next task re-leases
                    pool.release(handle)
                    lease_ex.worker_handle = None
            else:
                pool.release(handle)
        if reply.get("ok") and "arena_key" in reply:
            # Worker wrote the result straight into the shared arena:
            # take bookkeeping ownership and answer the head with a
            # stub — zero result bytes through daemon or head.
            key, size = reply["arena_key"], int(reply["size"])
            if self._table.adopt(key, size):
                self._send_reply(sock, {"req_id": req_id, "ok": True,
                                        "stored_key": key, "size": size})
            else:
                # Evicted between the worker's put and adoption (only
                # possible on eviction-mode arenas): ObjectPullError is
                # system-retriable — the head re-runs the task instead
                # of surfacing a user failure.
                from ray_tpu._private.dataplane import ObjectPullError
                self._reply(sock, req_id, error=ObjectPullError(
                    f"worker result {key} vanished from the arena "
                    "before adoption"))
            return
        if reply.get("ok") and "parts" in reply:
            # Per-element worker results: arena entries get adopted;
            # inline elements bigger than the stub limit still stay
            # daemon-resident via table.put (arena was full).
            store_limit = msg.get("store_limit", 0)
            out_parts = []
            inline_bytes = 0
            for i, p in enumerate(reply["parts"]):
                if "arena_key" in p:
                    if not self._table.adopt(p["arena_key"], p["size"]):
                        from ray_tpu._private.dataplane import \
                            ObjectPullError
                        self._reply(sock, req_id, error=ObjectPullError(
                            f"worker result {p['arena_key']} vanished "
                            "from the arena before adoption"))
                        return
                    out_parts.append({"stored_key": p["arena_key"],
                                      "size": p["size"]})
                elif store_limit and len(p["value"]) > store_limit:
                    key = (f"obj-{self._uid}-s{self._session_n}-"
                           f"{req_id}-r{i}")
                    self._table.put(key, p["value"])
                    out_parts.append({"stored_key": key,
                                      "size": len(p["value"])})
                else:
                    out_parts.append({"value": p["value"]})
                    inline_bytes += len(p["value"])
            self._send_reply(sock, {"req_id": req_id, "ok": True,
                                    "parts": out_parts},
                             nbytes=inline_bytes)
            return
        if reply.get("ok"):
            payload = reply["value"]
            store_limit = msg.get("store_limit", 0)
            num_returns = msg.get("num_returns", 1)
            if num_returns > 1 and store_limit and \
                    len(payload) > store_limit:
                # Split per return element (one extra deserialize on the
                # big path only; small results forward untouched below).
                self._reply_result(sock, req_id, _loads(payload),
                                   store_limit, num_returns)
            elif store_limit and len(payload) > store_limit:
                key = f"obj-{self._uid}-s{self._session_n}-{req_id}"
                self._table.put(key, payload)
                self._send_reply(sock, {"req_id": req_id, "ok": True,
                                        "stored_key": key,
                                        "size": len(payload)})
            else:
                self._send_reply(sock, {"req_id": req_id, "ok": True,
                                        "value": payload},
                                 nbytes=len(payload))
        else:
            self._send_reply(
                sock, {"req_id": req_id, "ok": False,
                       "error": reply["error"]},
                nbytes=len(reply["error"]))

    #: frame kinds that run user code and hold node resources; data-
    #: plane/control frames (fetch_object, stats, ...) never count.
    _USER_CODE_KINDS = frozenset(
        {"execute_task", "create_actor", "actor_call"})

    def _handle_counted(self, sock, msg: dict) -> None:
        import time as _time

        from ray_tpu._private.event_stats import GLOBAL
        counted = msg.get("type") in self._USER_CODE_KINDS
        cpus = float(msg.get("num_cpus", 1.0)) if counted else 0.0
        if counted:
            with self._inflight_lock:
                self._inflight += 1
                self._inflight_cpu += cpus
        _t0 = _time.monotonic()
        try:
            self._handle(sock, msg)
        finally:
            # Per-handler daemon EventStats ride the next metrics_batch
            # to the head (/api/event_stats "cluster" view).
            GLOBAL.record(f"daemon.{msg.get('type') or 'frame'}",
                          _time.monotonic() - _t0)
            if counted:
                with self._inflight_lock:
                    self._inflight -= 1
                    self._inflight_cpu -= cpus

    def _handle(self, sock, msg: dict) -> None:
        req_id = msg.get("req_id", 0)
        kind = msg.get("type")
        try:
            if kind == "execute_task":
                if self._task_uses_worker_process(msg):
                    self._execute_on_worker(sock, msg, req_id)
                    return
                ctx = msg.get("trace_ctx")
                fn = self._load_function(msg["fn_id"], msg.get("fn_bytes"))
                # Marker resolution is the daemon's arg-pull stage:
                # data-plane pulls inside record as child spans of it.
                with _trace_span(ctx, "data::resolve_args", "pull"):
                    args, kwargs = self._resolve_markers(
                        *_loads(msg["payload"]))
                with _trace_span(ctx, f"task::{msg.get('name', '')}",
                                 "execute"):
                    result = self._run_in_env(msg, fn, args, kwargs)
                self._reply_result(sock, req_id, result,
                                   msg.get("store_limit", 0),
                                   msg.get("num_returns", 1))
            elif kind == "create_actor":
                ctx = msg.get("trace_ctx")
                cls = self._load_function(msg["fn_id"], msg.get("fn_bytes"))
                with _trace_span(ctx, "data::resolve_args", "pull"):
                    args, kwargs = self._resolve_markers(
                        *_loads(msg["payload"]))
                with _trace_span(ctx, f"actor_init::{msg.get('name', '')}",
                                 "execute"):
                    instance = self._run_in_env(msg, cls, args, kwargs)
                self._actors[msg["actor_id"]] = instance
                self._actor_tpu_ids[msg["actor_id"]] = msg.get("tpu_ids")
                self._reply(sock, req_id, value=None)
            elif kind == "actor_call":
                ctx = msg.get("trace_ctx")
                instance = self._actors[msg["actor_id"]]
                method = getattr(instance, msg["method"])
                with _trace_span(ctx, "data::resolve_args", "pull"):
                    args, kwargs = self._resolve_markers(
                        *_loads(msg["payload"]))
                # Methods inherit the chips reserved at actor creation.
                msg = dict(msg,
                           tpu_ids=self._actor_tpu_ids.get(msg["actor_id"]))
                # The span brackets the coroutine run too (async actor
                # methods execute inside asyncio.run, not at call time).
                with _trace_span(ctx, f"actor_task::{msg.get('name', '')}",
                                 "execute"):
                    result = self._run_in_env(msg, method, args, kwargs)
                    import inspect
                    if inspect.iscoroutine(result):
                        import asyncio
                        result = asyncio.run(result)
                self._reply_result(sock, req_id, result,
                                   msg.get("store_limit", 0),
                                   msg.get("num_returns", 1))
            elif kind == "destroy_actor":
                self._actors.pop(msg["actor_id"], None)
                self._actor_tpu_ids.pop(msg["actor_id"], None)
                self._reply(sock, req_id, value=None)
            elif kind == "fetch_object":
                with self._table.pinned(msg["key"]) as raw:
                    if raw is None:
                        raise KeyError(
                            f"object payload {msg['key']} is not resident "
                            "on this node (already freed?)")
                    data = bytes(raw)
                self._send_reply(sock, {"req_id": req_id, "ok": True,
                                        "raw": data},
                                 nbytes=len(data))
            elif kind == "free_object":
                self._table.free(msg["key"])
                self._reply(sock, req_id, value=None)
            elif kind == "adopt_object":
                # Worker-process put (distributed ownership): the worker
                # wrote the payload straight into the shared arena; this
                # node takes lifetime ownership (spill-liveness
                # bookkeeping lives with the table's own lock
                # discipline, dataplane.NodeObjectTable.adopt).
                self._reply(sock, req_id, value=self._table.adopt(
                    msg["key"], msg["size"]))
            elif kind == "push_object":
                # Tree broadcast (runs on this frame's own _route_frame
                # thread, so a GB-scale landing never stalls the recv
                # loop).
                self._reply(sock, req_id,
                            value=self._handle_push_object(msg))
            elif kind == "profile":
                # Self-sampled stacks (reference: profile_manager.py
                # py-spy-on-demand, here cooperative — no ptrace). A
                # pid field retargets the burst at a pool worker via
                # its request pipe. Runs on a per-message thread
                # (_route_frame), so the seconds-long burst never
                # stalls the daemon recv loop.
                from ray_tpu._private.profiling import profile_self
                from ray_tpu._private.ray_config import \
                    runtime_config_value
                cap = float(runtime_config_value(
                    "profile_max_duration_s", 60.0))
                duration = min(float(msg.get("duration", 5.0)), cap)
                hz = int(msg.get("hz", 100))
                fmt = msg.get("fmt", "folded")
                pid = msg.get("pid")
                if pid is not None and int(pid) != os.getpid():
                    self._reply(sock, req_id,
                                value=self._profile_worker(
                                    int(pid), duration, hz, fmt))
                else:
                    self._reply(sock, req_id, value=profile_self(
                        duration, hz, fmt))
            elif kind == "stats":
                self._reply(sock, req_id, value={
                    "transfer": dict(self._table.stats),
                    "table": self._table.usage(),
                    "num_actors": len(self._actors),
                    "leases": len(self._lease_executors),
                    "lease_tasks_total": self._lease_tasks_total,
                    "pool_workers": (len(self._pool._all)
                                     if self._pool is not None else 0),
                })
            elif kind == "shutdown":
                self._stop.set()
            else:
                raise ValueError(f"unknown message type {kind!r}")
        except BaseException as exc:  # noqa: BLE001 - ship to the head
            try:
                self._reply(sock, req_id, error=exc,
                            tb=traceback.format_exc())
            except OSError:
                pass

    def _serve_health_channel(self) -> None:
        """Dedicated liveness socket: echo pings on a thread of its own,
        so the head can tell 'process hung' from 'data channel busy'.
        The connect retries with backoff — the head declares nodes that
        never open this channel dead, so one refused connect (listener
        backlog during a mass join) must not be fatal."""
        from ray_tpu._private.channel import Backoff
        bo = Backoff(0.2, 5.0)
        while not self._stop.is_set():
            try:
                hc = socket.create_connection(self.head_address,
                                              timeout=10)
                hc.settimeout(None)
                _send_frame(hc, _dumps({"type": "health_channel",
                                        "node_id": self.node_id_hex}))
                bo.reset()  # connected: a later drop backs off afresh
                # New channel == new peer state, BOTH directions: re-ship
                # every component snapshot (a restarted head starts from
                # nothing) and forget the old head's digest (the new
                # head's version counter restarts near zero).
                self.syncer_reporter.reset_peer()
                self.cluster_digest.reset()
                while not self._stop.is_set():
                    if _chaos.ACTIVE:
                        _chaos.maybe_inject("daemon.health.recv", hc)
                    ping = _loads(_recv_frame(hc))
                    self.cluster_digest.apply(
                        ping.get("cluster_digest"))
                    if _chaos.ACTIVE:
                        _chaos.maybe_inject("daemon.health.send", hc)
                    _send_frame(hc, _dumps(
                        {"type": "pong",
                         "sync": self.syncer_reporter.poll()}))
                return
            except (ConnectionError, OSError):
                bo.sleep()

    def _run_in_env(self, msg: dict, fn, args, kwargs):
        # Publish the head-assigned chip ids through the worker context so
        # ray_tpu.get_tpu_ids() works inside remotely executed tasks.
        import types

        from ray_tpu._private import ray_logging
        from ray_tpu._private.runtime import _task_context
        name = msg.get("name") or ""
        if name and ray_logging.markers_enabled():
            # In-daemon execution writes to the daemon's captured
            # streams; the marker attributes subsequent output to this
            # task (actor calls: `Cls.method pid=` driver prefixes).
            ray_logging.emit_task_marker(name)
        _task_context.spec = types.SimpleNamespace(
            _tpu_ids=msg.get("tpu_ids"), actor_id=None,
            name=msg.get("name", ""),
            task_id_hex=msg.get("task_id"))
        try:
            renv = msg.get("runtime_env")
            if renv:
                from ray_tpu._private import runtime_env as _renv
                _renv.setup(renv)
                with _renv.applied(renv):
                    return fn(*args, **kwargs)
            return fn(*args, **kwargs)
        finally:
            _task_context.spec = None

    def run(self, reconnect_window: Optional[float] = None) -> None:
        """Connect, register, and serve. On connection loss (head died
        or restarted) the daemon KEEPS its actors and object table and
        retries the head address for ``reconnect_window`` seconds — a
        restarted head (gcs_store_path persistence) rebinds the resident
        actors on re-registration (reference: raylet surviving GCS
        restart + resubscribe). An orderly head shutdown frame exits
        immediately.

        ``reconnect_window=None`` (the CLI default) reads
        ``RAY_TPU_head_failover_window_s`` — wide enough (120s) for a
        supervisor-restarted or standby head to come up, replay its
        gcs_store, and accept this daemon's re-registration."""
        import time as _time

        from ray_tpu._private.channel import Backoff
        global _current_daemon
        _current_daemon = self
        if reconnect_window is None:
            from ray_tpu._private.ray_config import runtime_config_value
            reconnect_window = float(
                runtime_config_value("head_failover_window_s", 120.0))
        ever_registered = False
        deadline = _time.monotonic() + max(reconnect_window, 0.0)
        # Jittered backoff: after a head restart every daemon in the
        # cluster re-dials at once — without jitter they'd hammer the
        # fresh listener in lockstep (thundering herd).
        bo = Backoff(0.2, 2.0)
        try:
            while not self._stop.is_set():
                self._session_registered = False
                try:
                    self._serve_once()
                except _wire.ProtocolMismatch:
                    raise  # permanent: retrying a version rejection spins
                except (ConnectionError, OSError) as exc:
                    if self._session_registered:
                        pass  # live session dropped; fall through, retry
                    elif reconnect_window <= 0:
                        raise
                    last_exc = exc
                if self._stop.is_set():
                    break
                if self._session_registered:
                    ever_registered = True
                    # A real session dropped — fresh reconnect window.
                    deadline = _time.monotonic() + reconnect_window
                    bo.reset()
                if reconnect_window <= 0 or _time.monotonic() >= deadline:
                    if not ever_registered:
                        raise ConnectionError(
                            f"could not join head {self.head_address} "
                            f"within {reconnect_window}s: {last_exc}")
                    logger.warning(
                        "Head %s unreachable for %.0fs; daemon exiting",
                        self.head_address, reconnect_window)
                    try:
                        from ray_tpu._private import builtin_metrics
                        builtin_metrics.daemon_redials().inc(
                            tags={"outcome": "gave_up"})
                    except Exception:  # noqa: BLE001 - exit path
                        pass
                    break
                bo.sleep()
        finally:
            # Any exit path — orderly shutdown, window expiry, or an
            # unexpected error (corrupt frame, bad ack) — releases the
            # object server port, worker pool, and the shm arena.
            self._teardown()

    def _teardown(self) -> None:
        if self._log_monitor is not None:
            self._log_monitor.stop()
        if self._metrics_agent is not None:
            self._metrics_agent.stop()
        if self._object_server is not None:
            self._object_server.close()
        if self._pool is not None:
            self._pool.shutdown()
        self._table.close()
        try:  # table.close() already unlinked every spilled file
            os.rmdir(self._spill_dir)
        except OSError:
            pass

    def _serve_once(self) -> None:
        """One connect-register-serve session against the head. Raises
        ConnectionError/OSError when the connection drops."""
        self._session_n += 1
        self._sock = socket.create_connection(self.head_address)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        # The IP this daemon uses to reach the head is the one peers (and
        # the head) can reach IT on — bind AND advertise the object server
        # there (object payloads are served unauthenticated, so the
        # exposure policy must match the control plane's, never 0.0.0.0).
        from ray_tpu._private.dataplane import ObjectServer
        local_ip = self._sock.getsockname()[0]
        if self._object_server is not None and \
                self._object_server_host != local_ip:
            # The head-facing interface changed (multi-homed host / head
            # moved): the advertised address must match the bind.
            self._object_server.close()
            self._object_server = None
        if self._object_server is None:  # survives same-IP reconnects
            self._object_server = ObjectServer(self._table, host=local_ip)
            self._object_server_host = local_ip
        _send_frame(self._sock, _dumps({
            "type": "register",
            "protocol": _wire.PROTOCOL_VERSION,
            "resources": self.resources,
            "labels": self.labels,
            "object_addr": (local_ip, self._object_server.port),
            "store_name": self._table.arena_name,
            # A restarted head (gcs persistence) rebinds these.
            "resident_actors": list(self._actors.keys()),
            # Our previous incarnation (0 = first life): a head that
            # fenced that epoch knows any residue we still carry is
            # stale and must not be rebound.
            "prev_epoch": self._node_epoch,
        }), self._send_lock)
        # Everything after the raw register frame flows through the
        # resilient channel (v7 seq envelopes): the head's first
        # enveloped frame is the "registered" ack at seq 1.
        from ray_tpu._private.channel import (ChannelBroken,
                                              ResilientChannel)
        from ray_tpu._private.ray_config import make_ray_config
        _ccfg = make_ray_config(None)
        chan = ResilientChannel(
            self._sock, site="daemon",
            ring_bytes=int(_ccfg.channel_resend_ring_bytes),
            window_s=float(_ccfg.channel_reconnect_window_s),
            ack_every=int(_ccfg.channel_ack_every),
            ack_flush_ms=int(_ccfg.channel_ack_flush_ms))
        self._chan = chan
        # register_rejected arrives raw (the head never built a
        # channel for a rejected dial); recv_frame passes it through.
        ack = _loads(chan.recv_frame())
        if ack.get("type") == "register_rejected":
            # Version mismatch: surface the head's words and STOP —
            # reconnect-retrying a permanent rejection would spin.
            raise _wire.ProtocolMismatch(ack["error"])
        assert ack["type"] == "registered", ack
        self.node_id_hex = ack["node_id"]
        channel_token = ack.get("channel_token")
        # Adopt the minted incarnation epoch (v9): every frame we send
        # from here on is stamped with it, so a head that later fences
        # this incarnation drops (and counts) stale frames instead of
        # applying them.
        self._node_epoch = int(ack.get("node_epoch") or 0)
        chan.epoch = self._node_epoch
        self._session_registered = True
        if getattr(self, "_was_registered", False):
            # A re-registration (head restarted, or resume window blew):
            # the failover loop delivered us to a live head again.
            try:
                from ray_tpu._private import builtin_metrics
                builtin_metrics.daemon_redials().inc(
                    tags={"outcome": "reregistered"})
            except Exception:  # noqa: BLE001 - metrics best-effort
                pass
        self._was_registered = True
        logger.info("Registered with head %s as node %s",
                    self.head_address, self.node_id_hex[:12])
        session_id = ack.get("session_id")
        if session_id and self._spill_uri.startswith("session://"):
            # session:// roots under the driver session's shared dir —
            # only now (ack in hand) is the session id known. Earlier
            # spills (pre-registration work) stay on their local-dir
            # records; only new writes land durably.
            from ray_tpu._private.spill import SessionSpillBackend
            try:
                self._table.set_spill_backend(
                    SessionSpillBackend(session_id))
            except OSError:
                logger.exception("could not enable session:// spill")
        if session_id and self._log_monitor is None:
            self._start_log_streaming(session_id)
        if self._metrics_agent is None:
            from ray_tpu._private.metrics_agent import MetricsAgent
            agent = MetricsAgent(
                self._publish_metrics_batch, component="daemon",
                publish_profile=self._publish_profile_batch,
                publish_flow=self._publish_flow_batch)
            agent.add_collector(self._collect_daemon_metrics)
            self._metrics_agent = agent
        if self._use_worker_processes and not self._prestarted:
            # Warm the worker pool once per daemon (reference:
            # worker_pool.h PrestartWorkers): leases then pin an
            # already-started worker instead of paying a spawn.
            self._prestarted = True
            from ray_tpu._private.ray_config import make_ray_config
            if int(make_ray_config(None).worker_prestart_count) > 0:
                cpus = int(self.resources.get("CPU", 1) or 1)
                self._get_pool().prestart(min(cpus, 8))
        if not self._health_started:
            # Started ONCE per daemon (even across reconnects): the
            # health thread reconnects on its own, re-announcing
            # whatever node_id_hex currently holds.
            self._health_started = True
            threading.Thread(target=self._serve_health_channel,
                             name="ray_tpu-daemon-health",
                             daemon=True).start()
        # Single writer for this session's replies, keyed by the CHANNEL
        # (stable across resume socket swaps). A send failure parks the
        # sender until resume; only window exhaustion closes the channel,
        # which pops the recv loop below out of its read.
        sender = _CoalescingSender(
            chan, "reply_batch", on_fail=chan.close,
            name=f"reply-{self.node_id_hex[:8]}")
        self._reply_senders[chan] = sender
        try:
            while not self._stop.is_set():
                try:
                    raw = chan.recv_frame()
                except ChannelBroken:
                    if self._stop.is_set():
                        break
                    # Transient transport failure: re-dial and resume —
                    # the session (lease executors, resident actors,
                    # class queues) survives; unacked frames replay on
                    # both sides. Only a failed resume tears down.
                    if self._try_resume(chan, channel_token):
                        try:
                            from ray_tpu._private import builtin_metrics
                            builtin_metrics.daemon_redials().inc(
                                tags={"outcome": "resumed"})
                        except Exception:  # noqa: BLE001
                            pass
                        continue
                    raise ConnectionError(
                        "session channel lost (resume failed)")
                msgs = _decode_frames(raw)
                for msg in msgs:
                    # Inbound control frames are schema-checked before
                    # any handler sees them: a head from another build
                    # fails HERE with the exact field, not deep in a
                    # handler. (Typed binary frames are validated by
                    # construction, but the decoded dict re-checks —
                    # one rule set for both encodings.)
                    _wire.validate_message(msg)
                    if not self._route_frame(msg):
                        self._stop.set()
                        break
        finally:
            # Head session over: its leases are meaningless — retire the
            # executors and return their pinned workers.
            sender.close()
            self._reply_senders.pop(chan, None)
            chan.close()
            for ex in self._lease_executors.values():
                ex.stop()
            self._lease_executors.clear()
            # Queued work died with the head; temp slots must not
            # outlive the session that lent them capacity.
            for cq in self._class_queues.values():
                cq.close()
            self._class_queues.clear()
            try:
                self._sock.close()
            except OSError:
                pass

    def _try_resume(self, chan, token: Optional[str]) -> bool:
        """Re-dial the head and resume a broken session channel.

        True: the channel re-attached (session state survives, unacked
        frames replayed both ways). False: resume impossible — rejected
        by the head, window exhausted, or orderly stop — and the caller
        tears the session down for a full re-register."""
        import time as _time

        from ray_tpu._private.channel import (Backoff, close_socket,
                                              connection_refused)
        if not token:
            return False
        deadline = (chan.broken_at or _time.monotonic()) + chan.window_s
        bo = Backoff(0.2, 2.0)
        refused = 0
        while not self._stop.is_set() and _time.monotonic() < deadline:
            sock = None
            try:
                sock = socket.create_connection(self.head_address,
                                                timeout=5)
                sock.settimeout(10)
                try:
                    sock.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                except OSError:
                    pass
                if _chaos.ACTIVE:
                    # A partition must blackhole the resume path too —
                    # otherwise a "partitioned" daemon could quietly
                    # re-attach mid-blackhole.
                    _chaos.maybe_inject("daemon.resume.send", sock)
                _send_frame(sock, _dumps({
                    "type": "resume",
                    "protocol": _wire.PROTOCOL_VERSION,
                    "node_id": self.node_id_hex,
                    "token": token,
                    "epoch": self._node_epoch,
                    "last_seq": chan.in_seq}))
                reply = _loads(_recv_frame(sock))
                if reply.get("type") == "fenced":
                    # This incarnation was declared dead while we were
                    # unreachable; head-side, its actors died with it
                    # (exactly once). Drop the stale residents NOW so
                    # the coming re-registration cannot offer them for
                    # rebinding — a restarted copy may already be
                    # running elsewhere, and two live instances of one
                    # detached actor is the split-brain this fence
                    # exists to prevent.
                    logger.warning(
                        "session fenced (incarnation %d declared dead); "
                        "dropping %d stale resident actors and "
                        "re-registering", self._node_epoch,
                        len(self._actors))
                    self._actors.clear()
                    self._actor_tpu_ids.clear()
                    close_socket(sock)
                    return False
                if reply.get("type") != "resumed":
                    # Head restarted / node already declared dead: a
                    # full re-register is the right (and fast) path.
                    logger.warning("channel resume rejected: %s",
                                   reply.get("error"))
                    close_socket(sock)
                    return False
                sock.settimeout(None)
                if chan.attach(sock, int(reply.get("last_seq", 0))):
                    self._sock = sock  # SIGTERM handler pops the reader
                    return True
                close_socket(sock)
                return False
            except (ConnectionError, OSError) as exc:
                if sock is not None:
                    close_socket(sock)
                if connection_refused(exc):
                    # Nothing is LISTENING at the head address: the head
                    # process is gone, and with it the channel ring this
                    # resume would replay into. Burning the rest of the
                    # resume window here would eat into the failover
                    # window — bail to the outer re-register loop, which
                    # keeps re-dialing for head_failover_window_s and
                    # can join a REBORN head. A couple of confirmations
                    # guard against one stray RST during a restart race.
                    refused += 1
                    if refused >= 3:
                        logger.warning(
                            "head %s refused %d consecutive resume "
                            "dials (process gone); falling back to "
                            "re-register", self.head_address, refused)
                        return False
                else:
                    refused = 0
                bo.sleep()
        return False

    def _start_log_streaming(self, session_id: str) -> None:
        """Join the driver session's log tree (the registration ack
        carries the session id): this daemon's own stdout/stderr move
        into per-proc ``raylet-<pid>`` files, its python logging onto a
        structured ``raylet-<pid>.log``, and a LogMonitor tails every
        capture file this process creates (raylet + spawned workers),
        shipping batches head-ward."""
        from ray_tpu._private import ray_logging
        from ray_tpu._private.log_monitor import LogMonitor
        try:
            log_dir = ray_logging.setup_session(
                session_id, f"node-{(self.node_id_hex or '')[:12]}")
        except OSError:
            logger.exception("could not join session log dir")
            return
        ray_logging.attach_file_logging(log_dir)
        redirected = ray_logging.redirect_process_streams(log_dir)
        if redirected:
            # Streams are captured (not a tty): in-daemon task/actor
            # execution can announce task names via stream markers —
            # actor calls show `Cls.method pid=` in driver streaming
            # like worker-subprocess output does.
            os.environ[ray_logging.MARKER_ENV] = "1"
        monitor = LogMonitor(self._publish_log_batch)
        for path, source in redirected:
            monitor.add_file(path, "raylet", os.getpid(), source)
        ray_logging.register_capture_callback(monitor.add_file)
        self._log_monitor = monitor

    def _announce_spilled(self, key: str, uri: str, size: int) -> None:
        """Durable-spill notice (NodeObjectTable.on_spilled): the head
        adds the URI to its location table so this daemon's death
        restores the object from disk instead of re-running lineage.
        Best-effort between sessions — a re-register re-announces
        nothing, but the spill record survives on disk either way."""
        chan = self._chan
        sender = self._reply_senders.get(chan) if chan is not None \
            else None
        if sender is not None:
            sender.send({"type": "object_spilled", "key": key,
                         "uri": uri, "size": int(size)})

    def _announce_unspilled(self, key: str) -> None:
        """Retraction (restore-promotion or free deleted the file)."""
        chan = self._chan
        sender = self._reply_senders.get(chan) if chan is not None \
            else None
        if sender is not None:
            sender.send({"type": "object_unspilled", "key": key})

    def _publish_log_batch(self, batch: dict) -> bool:
        """Ship one tail batch through the session's coalescing reply
        sender (the socket's single writer — log frames interleave
        safely with task replies). Logs are best-effort: between head
        sessions there is no sender and the batch is dropped; the full
        text stays on disk for `ray-tpu logs`."""
        chan = self._chan
        sender = self._reply_senders.get(chan) if chan is not None \
            else None
        if sender is None:
            return False
        msg = dict(batch)
        msg["type"] = "log_batch"
        msg["node_id"] = self.node_id_hex or ""
        return bool(sender.send(msg))

    def _publish_metrics_batch(self, batch: dict) -> bool:
        """Ship one metrics batch (the daemon's own registry snapshot,
        or a worker's piggybacked batch) through the session's reply
        sender. Returning False (no live head session) makes the agent
        resend a full snapshot once the channel recovers."""
        chan = self._chan
        sender = self._reply_senders.get(chan) if chan is not None \
            else None
        if sender is None:
            return False
        msg = dict(batch)
        msg["type"] = "metrics_batch"
        msg["node_id"] = self.node_id_hex or ""
        if msg.get("component") == "daemon":
            # Piggyback this daemon's control-loop EventStats (additive
            # wire-v9 field) so /api/event_stats sees every node, not
            # just the head process. Worker batches relayed through the
            # same sink keep their own identity — no stats attached.
            from ray_tpu._private.event_stats import GLOBAL
            stats = GLOBAL.summary()
            if stats:
                msg["event_stats"] = stats
        return bool(sender.send(msg))

    def _publish_profile_batch(self, batch: dict) -> bool:
        """Ship one folded-stack window (the daemon's own profiler, or
        a worker's piggybacked window) as a ``profile_batch`` push.
        Additive post-v9: an old head's recv loop drops the unknown
        push type on the floor, so mixed clusters stay compatible."""
        chan = self._chan
        sender = self._reply_senders.get(chan) if chan is not None \
            else None
        if sender is None:
            return False
        msg = dict(batch)
        msg["type"] = "profile_batch"
        msg["node_id"] = self.node_id_hex or ""
        return bool(sender.send(msg))

    def _publish_flow_batch(self, batch: dict) -> bool:
        """Ship one drained transfer-ledger window (this daemon's own
        FlowRecorder, or a worker's piggybacked batch) as a
        ``flow_batch`` push. Additive post-v9: an old head's recv loop
        drops the unknown push type on the floor."""
        chan = self._chan
        sender = self._reply_senders.get(chan) if chan is not None \
            else None
        if sender is None:
            return False
        msg = dict(batch)
        msg["type"] = "flow_batch"
        msg["node_id"] = self.node_id_hex or ""
        return bool(sender.send(msg))

    def _profile_worker(self, pid: int, duration: float, hz: int,
                        fmt: str):
        """Relay a profile burst to the pool worker owning ``pid`` over
        its request pipe (cooperative — the worker samples itself, no
        ptrace/py-spy needed on the node). The pipe is one-in-flight: a
        worker mid-task starts sampling when its current task ends."""
        pool = self._pool
        handle = None
        if pool is not None:
            for w in list(pool._all):
                if w.pid == pid:
                    handle = w
                    break
        if handle is None:
            raise ValueError(
                f"pid {pid} is not a live worker of this node")
        reply = handle.request({"type": "profile", "duration": duration,
                                "hz": hz},
                               timeout=duration + 30)
        if not reply.get("ok"):
            raise RuntimeError(reply.get("error")
                               or "worker profile failed")
        counts = reply.get("stacks") or {}
        if fmt == "dict":
            return counts
        if fmt == "speedscope":
            from ray_tpu._private.profiling import folded_to_speedscope
            return folded_to_speedscope(counts, name=f"worker-{pid}",
                                        hz=hz)
        return "\n".join(f"{k} {v}" for k, v in sorted(counts.items()))

    def _collect_daemon_metrics(self) -> None:
        """Refresh daemon-side gauges before each export snapshot."""
        pool = self._pool
        if pool is not None:
            record = getattr(pool, "record_metrics", None)
            if record is not None:
                record()

    def _route_frame(self, msg: dict) -> bool:
        """Route one inbound control message (recv-loop thread only).
        Returns False for shutdown."""
        if msg.get("type") == "shutdown":
            return False
        # Serialize function installation: cache raw bytes here on
        # the recv thread, not in the handler threads.
        fb = msg.get("fn_bytes")
        if fb is not None and msg.get("fn_id") is not None:
            self._fn_raw.setdefault(msg["fn_id"], fb)
        lease_id = msg.get("lease_id")
        if msg.get("type") == "drop_lease":
            ex = self._lease_executors.pop(lease_id, None)
            if ex is not None:
                ex.stop()
        elif msg.get("type") == "spill_lease":
            ex = self._lease_executors.get(lease_id)
            if ex is not None:
                ex.spill()
        elif msg.get("type") == "unspill_lease":
            ex = self._lease_executors.get(lease_id)
            if ex is not None:
                ex.unspill()
        elif msg.get("type") == "reclaim_tasks":
            self._reclaim_tasks(self._chan, msg)
        elif lease_id is not None:
            # Leased task: onto the class's shared local-dispatch queue
            # (CPU classes — the daemon picks the slot), or the lease's
            # strict-FIFO serial executor (TPU classes: chip ids were
            # accounted to this lease, overlap would double-book them).
            ex = self._lease_executors.get(lease_id)
            if ex is None:
                cq = None
                class_id = msg.get("class_id")
                if class_id is not None and not msg.get("tpu_ids"):
                    cq = self._class_queues.get(class_id)
                    if cq is None:
                        cq = _ClassQueue(self, class_id)
                        self._class_queues[class_id] = cq
                ex = _LeaseExecutor(self, lease_id, cq)
                self._lease_executors[lease_id] = ex
            self._lease_tasks_total += 1
            if ex.spilled:
                # Spilled SERIAL lease (a task blocked in a nested get):
                # late frames bypass the serial queue too.
                threading.Thread(target=self._handle_counted,
                                 args=(self._chan, msg),
                                 daemon=True).start()
            else:
                ex.submit(self._chan, msg)
        else:
            # Pass THIS session's channel: a handler outliving the
            # session replies into a closed channel (dropped), never
            # into a later session whose fresh req_id counter would
            # collide with this frame's req_id.
            threading.Thread(target=self._handle_counted,
                             args=(self._chan, msg),
                             daemon=True).start()
        return True


def run_node(address: str, *, num_cpus: float = 1.0, num_tpus: float = 0.0,
             memory: float = 1 << 30,
             resources: Optional[Dict[str, float]] = None,
             labels: Optional[dict] = None,
             object_store_memory: int = 1 << 28,
             spill_dir: Optional[str] = None) -> None:
    """Entry point for `ray-tpu start --address host:port` and
    `python -m ray_tpu._private.multinode`."""
    host, _, port = address.rpartition(":")
    node_resources: Dict[str, float] = {"CPU": float(num_cpus),
                                        "memory": float(memory)}
    if num_tpus:
        node_resources["TPU"] = float(num_tpus)
    if resources:
        node_resources.update(resources)
    daemon = NodeDaemon((host or "127.0.0.1", int(port)), node_resources,
                        labels,
                        object_store_memory=int(object_store_memory),
                        spill_dir=spill_dir)

    # Graceful SIGTERM: pop run() out of its recv loop so its finally
    # runs the ONE _teardown (arena unlink, pool shutdown, spill-dir
    # removal). The handler itself must not touch table locks — a
    # SIGTERM landing mid-_teardown would self-deadlock on the
    # non-reentrant lock the suspended frame already holds. (SIGKILL
    # cannot be trapped — the stale reapers cover that.)
    import signal as _signal

    def _terminate(_signum, _frame):
        daemon._stop.set()
        sock = daemon._sock
        if sock is not None:
            _close_quiet(sock)

    with contextlib.suppress(ValueError):  # non-main thread: skip
        _signal.signal(_signal.SIGTERM, _terminate)
    daemon.run()


def _main() -> None:
    import argparse
    import json
    parser = argparse.ArgumentParser(
        description="ray_tpu node daemon: join a head and execute tasks")
    parser.add_argument("--address", required=True,
                        help="head host:port (ray_tpu.start_head_server)")
    parser.add_argument("--num-cpus", type=float, default=1.0)
    parser.add_argument("--num-tpus", type=float, default=0.0)
    parser.add_argument("--memory", type=float, default=float(1 << 30))
    parser.add_argument("--resources", type=str, default=None,
                        help='extra resources as JSON, e.g. \'{"spot": 1}\'')
    parser.add_argument("--labels", type=str, default=None,
                        help="node labels as JSON (autoscaler providers "
                             "tag their nodes here)")
    parser.add_argument("--object-store-memory", type=float,
                        default=float(1 << 28),
                        help="bytes for this node's object table (shm "
                             "arena when available)")
    parser.add_argument("--spill-dir", type=str, default=None,
                        help="directory for disk spill of cold objects "
                             "under memory pressure (default: a per-"
                             "daemon dir under the system temp dir)")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO)
    run_node(args.address, num_cpus=args.num_cpus, num_tpus=args.num_tpus,
             memory=args.memory,
             resources=json.loads(args.resources) if args.resources
             else None,
             labels=json.loads(args.labels) if args.labels else None,
             object_store_memory=int(args.object_store_memory),
             spill_dir=args.spill_dir)


if __name__ == "__main__":
    # `python -m` runs this file as __main__ — delegate to the canonical
    # import so the daemon's classes are identical to the ones the head
    # pickles by reference (isinstance across the wire depends on it).
    from ray_tpu._private.multinode import _main as _canonical_main

    _canonical_main()
