"""Node resource detection — TPU chips as first-class resources.

The reference autodetects GPUs and assigns CUDA_VISIBLE_DEVICES
(python/ray/_private/resource_spec.py:175 _autodetect_num_gpus). Here the
accelerator layer is TPU-native: chips come from ``jax.devices()``; the ICI
topology (e.g. v4-8) is exposed as an ``accelerator_type:TPU-<gen>`` marker
resource plus node metadata used by placement groups to map bundles onto mesh
slices.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass
class NodeResources:
    num_cpus: float
    num_tpus: float
    memory_bytes: float
    tpu_platform: str = ""  # e.g. "tpu v4"
    tpu_topology: str = ""  # e.g. "2x2x1"
    custom: Dict[str, float] = field(default_factory=dict)

    def to_resource_map(self) -> Dict[str, float]:
        resources = {"CPU": self.num_cpus, "memory": self.memory_bytes}
        if self.num_tpus:
            resources["TPU"] = self.num_tpus
            if self.tpu_platform:
                marker = "accelerator_type:" + self.tpu_platform.upper().replace(" ", "-")
                resources[marker] = 1.0
        resources.update(self.custom)
        return resources


def _autodetect_num_tpus() -> tuple[float, str]:
    """Count local TPU chips without initializing a TPU runtime if possible.

    Honors TPU_VISIBLE_CHIPS/TPU_CHIPS_PER_HOST overrides; otherwise asks JAX
    (only if JAX has already been imported or detection is explicitly enabled,
    to keep `init()` cheap on CPU-only hosts and to avoid grabbing the chips
    from the scheduler process).
    """
    env = os.environ.get("RAY_TPU_NUM_CHIPS")
    if env is not None:
        return float(env), os.environ.get("RAY_TPU_PLATFORM", "tpu")
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return float(len([c for c in visible.split(",") if c.strip() != ""])), "tpu"
    import sys
    if "jax" in sys.modules:
        try:
            import jax
            devices = [d for d in jax.devices() if d.platform == "tpu"]
            if devices:
                return float(len(devices)), getattr(
                    devices[0], "device_kind", "tpu")
        except Exception:  # noqa: BLE001 - no TPU runtime present
            pass
    return 0.0, ""


def detect_node_resources(
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    memory: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
) -> NodeResources:
    if num_cpus is None:
        num_cpus = float(os.cpu_count() or 1)
    platform = ""
    if num_tpus is None:
        num_tpus, platform = _autodetect_num_tpus()
    if memory is None:
        try:
            page = os.sysconf("SC_PAGE_SIZE")
            phys = os.sysconf("SC_PHYS_PAGES")
            memory = float(page * phys) * 0.7
        except (ValueError, OSError):
            memory = 8e9
    from ray_tpu._private.task_spec import validate_resource_name
    for name in (resources or {}):
        validate_resource_name(name)
    return NodeResources(
        num_cpus=float(num_cpus),
        num_tpus=float(num_tpus),
        memory_bytes=float(memory),
        tpu_platform=platform,
        custom=dict(resources or {}),
    )
