"""Per-process log tailer: follows capture files created by
ray_logging, batches new lines, and hands them to a publish callback.

Analog of the reference's python/ray/_private/log_monitor.py, minus the
one-agent-per-node daemon: here every process that spawns captured
children (the head runtime, each NodeDaemon) runs its own LogMonitor
thread over exactly the files it created — so hosts that share a
session tmpdir never double-stream each other's output.

Guarantees:

- Bounded work per poll: at most ``MAX_BYTES_PER_POLL`` read per file
  and ``MAX_LINES_PER_BATCH`` lines per published batch (backpressure —
  a runaway worker can't wedge the daemon's event loop).
- Storm guard: consecutive identical lines collapse into the first
  occurrence plus a ``message repeated N times`` summary, so 10k
  copies of one line cost two published lines.
- Rotation: when a tailed file outgrows ``MAX_FILE_BYTES`` it is
  copytruncate-rotated (backups shifted, file truncated in place) —
  safe because all writers use O_APPEND, so post-truncate writes land
  at the new EOF.
- Publish returning False means "transport unavailable": the batch is
  DROPPED but offsets still advance (logs are best-effort streams; the
  full text stays on disk for `ray-tpu logs`).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private import builtin_metrics
from ray_tpu._private.ray_logging import TASK_MARKER

logger = logging.getLogger(__name__)

MAX_BYTES_PER_POLL = 128 * 1024
MAX_LINES_PER_BATCH = 500
#: Per-file size cap before copytruncate rotation.
MAX_FILE_BYTES = 16 * 1024 * 1024
BACKUP_COUNT = 3
POLL_INTERVAL_S = 0.2


class _TailState:
    """Cursor + per-stream metadata for one capture file."""

    __slots__ = ("path", "proc_name", "pid", "source", "pos", "partial",
                 "task_name", "last_line", "repeat")

    def __init__(self, path: str, proc_name: str, pid: int, source: str):
        self.path = path
        self.proc_name = proc_name
        self.pid = pid
        self.source = source
        self.pos = 0
        self.partial = b""       # trailing bytes with no newline yet
        self.task_name: Optional[str] = None
        self.last_line: Optional[str] = None
        self.repeat = 0          # suppressed duplicates of last_line


class LogMonitor:
    """Tails registered files and publishes line batches.

    ``publish(batch: dict) -> bool`` receives
    ``{"pid", "proc_name", "source", "task_name", "lines"}`` (the
    transport stamps node identity). Construct with ``start=False`` and
    drive :meth:`poll_once` directly in unit tests."""

    def __init__(self, publish: Callable[[Dict[str, Any]], bool], *,
                 start: bool = True,
                 max_file_bytes: int = MAX_FILE_BYTES):
        self._publish = publish
        self._max_file_bytes = max_file_bytes
        self._files: Dict[str, _TailState] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._run, name="ray_tpu-log-monitor", daemon=True)
            self._thread.start()

    # -- registration ------------------------------------------------------

    def add_file(self, path: str, proc_name: str, pid: int,
                 source: str) -> None:
        with self._lock:
            if path not in self._files:
                self._files[path] = _TailState(path, proc_name, pid, source)

    def remove_file(self, path: str) -> None:
        with self._lock:
            self._files.pop(path, None)

    # -- tailing -----------------------------------------------------------

    def poll_once(self) -> int:
        """One pass over all files; returns total lines published."""
        with self._lock:
            states = list(self._files.values())
        published = 0
        for st in states:
            try:
                published += self._poll_file(st)
            except Exception:  # noqa: BLE001 - one bad file != dead tailer
                logger.exception("log tail failed for %s", st.path)
        return published

    def _poll_file(self, st: _TailState) -> int:
        try:
            size = os.path.getsize(st.path)
        except OSError:
            return 0  # deleted/renamed away: keep state, file may return
        if size < st.pos:  # truncated (external rotation): restart
            st.pos = 0
            st.partial = b""
        if size == st.pos:
            return 0
        try:
            with open(st.path, "rb") as f:
                f.seek(st.pos)
                chunk = f.read(MAX_BYTES_PER_POLL)
        except OSError:
            return 0
        st.pos += len(chunk)
        data = st.partial + chunk
        parts = data.split(b"\n")
        st.partial = parts.pop()  # b"" when data ended on a newline
        lines = []
        for raw in parts:
            text = raw.decode("utf-8", "replace").rstrip("\r")
            if text.startswith(TASK_MARKER):  # consume, never forward
                st.task_name = text[len(TASK_MARKER):] or None
                continue
            lines.append(text)
        n = self._emit(st, lines)
        if st.pos >= self._max_file_bytes:
            self._rotate(st)
        return n

    def _emit(self, st: _TailState, lines: List[str]) -> int:
        """Apply the storm guard and publish in bounded batches."""
        out: List[str] = []
        for line in lines:
            if line == st.last_line:
                st.repeat += 1
                continue
            out.extend(self._drain_repeat(st))
            st.last_line = line
            out.append(line)
        out.extend(self._drain_repeat(st))
        total = 0
        dropped = 0
        for i in range(0, len(out), MAX_LINES_PER_BATCH):
            batch = {"pid": st.pid, "proc_name": st.proc_name,
                     "source": st.source, "task_name": st.task_name,
                     "lines": out[i:i + MAX_LINES_PER_BATCH]}
            try:
                if self._publish(batch):
                    total += len(batch["lines"])
                else:
                    dropped += len(batch["lines"])
            except Exception:  # noqa: BLE001 - drop batch, keep tailing
                dropped += len(batch["lines"])
                logger.exception("log publish failed")
        if total:
            builtin_metrics.log_lines().inc(total)
        if dropped:
            builtin_metrics.log_lines_dropped().inc(dropped)
        return total

    def _drain_repeat(self, st: _TailState) -> List[str]:
        if st.repeat == 0:
            return []
        n, st.repeat = st.repeat, 0
        return [f"[log_monitor] message repeated {n} times"]

    # -- rotation ----------------------------------------------------------

    def _rotate(self, st: _TailState) -> None:
        """Copytruncate: shift backups, truncate in place (writers keep
        their O_APPEND fds), reset the cursor."""
        path = st.path
        try:
            for i in range(BACKUP_COUNT - 1, 0, -1):
                src = f"{path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{path}.{i + 1}")
            with open(path, "rb") as f:
                data = f.read()
            with open(f"{path}.1", "wb") as f:
                f.write(data)
            os.truncate(path, 0)
        except OSError:
            logger.exception("log rotation failed for %s", path)
        st.pos = 0
        st.partial = b""

    # -- lifecycle ---------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.wait(POLL_INTERVAL_S):
            self.poll_once()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
        self.poll_once()  # final drain so short-lived output isn't lost
