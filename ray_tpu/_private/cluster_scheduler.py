"""Cluster-level resource scheduling: pick a node, then acquire on it.

The multi-node analog of the reference's two-level scheduler:
`ClusterTaskManager::ScheduleAndDispatchTasks` picks a node with a pluggable
policy (src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h — pack
until a utilization threshold, then spread), and the chosen node's
`LocalTaskManager` acquires resources. Here every node is virtual (the
process hosts all of them), but the accounting, policies, and failure
semantics mirror the reference:

* **Hybrid (DEFAULT)**: prefer nodes in id order while their critical
  resource utilization stays below the 50% threshold, else pick the
  least-utilized feasible node (spread).
* **SPREAD**: least-utilized feasible node, round-robin tie-break.
* **NodeAffinity**: the named node, falling back to hybrid iff ``soft``.
* **Placement groups** are reserved across nodes with PACK / SPREAD /
  STRICT_PACK / STRICT_SPREAD bundle policies
  (src/ray/raylet/scheduling/policy/bundle_scheduling_policy.h); on a TPU
  cluster a PG maps onto an ICI slice, so STRICT_PACK == one host and each
  bundle is one host's worth of chips.

Node death releases nothing back (the node's resources vanish with it);
the runtime handles task retry / actor restart / object reconstruction.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu._private.scheduler import ResourceScheduler, _fits
from ray_tpu.exceptions import PlacementGroupError

# Reference default: RAY_scheduler_spread_threshold = 0.5
# (src/ray/common/ray_config_def.h).
SPREAD_THRESHOLD = 0.5


class NodeState:
    def __init__(self, node_id: NodeID, resources: Dict[str, float],
                 is_head: bool = False, labels: Optional[dict] = None):
        self.node_id = node_id
        self.resources = dict(resources)
        self.local = ResourceScheduler(dict(resources))
        self.alive = True
        self.is_head = is_head
        self.labels = dict(labels or {})
        # Per-node TPU chip-slot allocator (the analog of per-node
        # CUDA_VISIBLE_DEVICES assignment in the reference).
        self.free_tpu_ids: List[int] = list(range(int(resources.get("TPU", 0))))

    def utilization(self) -> float:
        """Max used-fraction over resources with nonzero capacity (the
        'critical resource utilization' of the hybrid policy)."""
        worst = 0.0
        total = self.local.total
        avail = self.local.available
        for key, cap in total.items():
            if cap <= 0 or key.startswith("node:"):
                continue
            used = cap - avail.get(key, 0.0)
            worst = max(worst, used / cap)
        return worst


class _PGBundle:
    __slots__ = ("node_id", "resources", "available")

    def __init__(self, node_id: NodeID, resources: Dict[str, float]):
        self.node_id = node_id
        self.resources = dict(resources)
        self.available = dict(resources)


class _PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, strategy: str,
                 bundles: List[_PGBundle]):
        self.pg_id = pg_id
        self.strategy = strategy
        self.bundles = bundles


class ClusterResourceScheduler:
    """Owns every NodeState; all acquire/release flows through here."""

    def __init__(self):
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, NodeState] = {}
        self._node_order: List[NodeID] = []
        self._pgs: Dict[PlacementGroupID, _PlacementGroup] = {}
        self._spread_rr = 0  # round-robin cursor for SPREAD ties

    # -- membership -------------------------------------------------------

    def add_node(self, resources: Dict[str, float], is_head: bool = False,
                 labels: Optional[dict] = None,
                 node_id: Optional[NodeID] = None) -> NodeID:
        if node_id is None:
            node_id = NodeID.from_random()
        resources = dict(resources)
        # Every node advertises its identity resource, like the reference's
        # node:<ip> resource used by NodeAffinity internals.
        resources.setdefault(f"node:{node_id.hex()[:12]}", 1.0)
        if is_head:
            resources.setdefault("node:__internal_head__", 1.0)
        with self._lock:
            state = NodeState(node_id, resources, is_head, labels)
            self._nodes[node_id] = state
            self._node_order.append(node_id)
        return node_id

    def remove_node(self, node_id: NodeID) -> Optional[NodeState]:
        with self._lock:
            state = self._nodes.get(node_id)
            if state is None or not state.alive:
                return None
            state.alive = False
            self._node_order.remove(node_id)
            return state

    def node(self, node_id: NodeID) -> Optional[NodeState]:
        return self._nodes.get(node_id)

    def alive_nodes(self) -> List[NodeState]:
        with self._lock:
            return [self._nodes[n] for n in self._node_order]

    def nodes_snapshot(self) -> List[dict]:
        with self._lock:
            out = []
            for node_id, state in self._nodes.items():
                out.append({
                    "NodeID": node_id.hex(),
                    "Alive": state.alive,
                    "Resources": dict(state.resources),
                    "Available": dict(state.local.available)
                    if state.alive else {},
                    "IsHead": state.is_head,
                    "Labels": dict(state.labels),
                })
            return out

    # -- aggregate views (state API / ray.cluster_resources) --------------

    @property
    def total(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        with self._lock:
            for node_id in self._node_order:
                for k, v in self._nodes[node_id].local.total.items():
                    agg[k] = agg.get(k, 0.0) + v
        return agg

    @property
    def available(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        with self._lock:
            for node_id in self._node_order:
                for k, v in self._nodes[node_id].local.available.items():
                    agg[k] = agg.get(k, 0.0) + v
        return agg

    # -- node selection ---------------------------------------------------

    def _candidate_nodes(self, strategy) -> Tuple[List[NodeState], bool]:
        """Returns (ordered candidates, hard_affinity_failed_ok)."""
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        with self._lock:
            ordered = [self._nodes[n] for n in self._node_order]
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            target = None
            for state in ordered:
                if state.node_id.hex().startswith(strategy.node_id) or \
                        strategy.node_id == state.node_id.hex():
                    target = state
                    break
            if target is not None and target.alive:
                if strategy.soft:
                    rest = self._hybrid_order(
                        [s for s in ordered if s is not target])
                    return [target] + rest, True
                return [target], False
            if strategy.soft:
                # Soft affinity falls back to the DEFAULT/hybrid policy —
                # same ordering the native engine's pick_and_acquire uses.
                return self._hybrid_order(ordered), True
            return [], False
        if strategy == "SPREAD":
            with self._lock:
                self._spread_rr += 1
                rr = self._spread_rr
            ranked = sorted(
                ordered, key=lambda s: (round(s.utilization(), 6),))
            if ranked:
                # rotate equal-utilization prefix for round-robin behavior
                lowest = round(ranked[0].utilization(), 6)
                prefix = [s for s in ranked
                          if round(s.utilization(), 6) == lowest]
                rest = ranked[len(prefix):]
                k = rr % len(prefix)
                ranked = prefix[k:] + prefix[:k] + rest
            return ranked, False
        # DEFAULT / hybrid: pack onto nodes (in id order) under the spread
        # threshold, else least-utilized first.
        return self._hybrid_order(ordered), False

    @staticmethod
    def _hybrid_order(ordered):
        """Hybrid-policy candidate order (pack under the spread threshold in
        insertion order, then least-utilized first)."""
        under = [s for s in ordered if s.utilization() < SPREAD_THRESHOLD]
        over = sorted((s for s in ordered if s not in under),
                      key=lambda s: s.utilization())
        return under + over

    def is_feasible(self, resources: Dict[str, float],
                    pg_id: Optional[PlacementGroupID] = None,
                    bundle_index: int = -1, strategy=None) -> bool:
        with self._lock:
            if pg_id is not None:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    return False
                bundles = (pg.bundles if bundle_index < 0
                           else pg.bundles[bundle_index:bundle_index + 1])
                return any(_fits(b.resources, resources) for b in bundles)
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        if isinstance(strategy, NodeAffinitySchedulingStrategy) and \
                not strategy.soft:
            nodes, _ = self._candidate_nodes(strategy)
            return any(_fits(s.local.total, resources) for s in nodes)
        return any(_fits(s.local.total, resources)
                   for s in self.alive_nodes())

    def try_acquire(self, resources: Dict[str, float],
                    pg_id: Optional[PlacementGroupID] = None,
                    bundle_index: int = -1,
                    strategy=None) -> Optional[Tuple[NodeID, int]]:
        """Pick a node + acquire. Returns (node_id, bundle_index_used) or
        None if nothing is available right now. bundle_index_used is -1 when
        acquiring from a node's global pool."""
        if pg_id is not None:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None:
                    return None
                candidates = ([bundle_index] if bundle_index >= 0
                              else range(len(pg.bundles)))
                for i in candidates:
                    if i >= len(pg.bundles):
                        return None
                    b = pg.bundles[i]
                    node = self._nodes.get(b.node_id)
                    if node is None or not node.alive:
                        continue
                    if _fits(b.available, resources):
                        for k, v in resources.items():
                            b.available[k] = b.available.get(k, 0.0) - v
                        return b.node_id, i
                return None
        candidates, _ = self._candidate_nodes(strategy)
        for state in candidates:
            if not state.alive:
                continue
            if state.local.try_acquire(resources) is not None:
                return state.node_id, -1
        return None

    def release(self, resources: Dict[str, float],
                node_id: Optional[NodeID] = None,
                pg_id: Optional[PlacementGroupID] = None,
                bundle_index: int = -1) -> None:
        if pg_id is not None and bundle_index >= 0:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None or bundle_index >= len(pg.bundles):
                    return
                b = pg.bundles[bundle_index]
                node = self._nodes.get(b.node_id)
                if node is None or not node.alive:
                    return  # resources died with the node
                for k, v in resources.items():
                    b.available[k] = b.available.get(k, 0.0) + v
            return
        if node_id is None:
            return
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or not node.alive:
                return
        node.local.release(resources)

    def force_acquire(self, resources: Dict[str, float],
                      node_id: Optional[NodeID] = None,
                      pg_id: Optional[PlacementGroupID] = None,
                      bundle_index: int = -1) -> None:
        """Re-acquire previously released resources without an availability
        check (unblock path; may transiently overcommit)."""
        if pg_id is not None and bundle_index >= 0:
            with self._lock:
                pg = self._pgs.get(pg_id)
                if pg is None or bundle_index >= len(pg.bundles):
                    return
                b = pg.bundles[bundle_index]
                for k, v in resources.items():
                    b.available[k] = b.available.get(k, 0.0) - v
            return
        if node_id is None:
            return
        with self._lock:
            node = self._nodes.get(node_id)
        if node is not None and node.alive:
            node.local.force_acquire(resources)

    # -- TPU chip slots ---------------------------------------------------

    def take_tpu_ids(self, node_id: NodeID, n: int) -> Optional[List[int]]:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is None or len(node.free_tpu_ids) < n:
                return None
            return [node.free_tpu_ids.pop() for _ in range(n)]

    def return_tpu_ids(self, node_id: NodeID, ids: List[int]) -> None:
        with self._lock:
            node = self._nodes.get(node_id)
            if node is not None and node.alive:
                node.free_tpu_ids.extend(ids)

    # -- placement groups -------------------------------------------------

    def create_placement_group(self, pg_id: PlacementGroupID,
                               bundles: List[Dict[str, float]],
                               strategy: str = "PACK") -> None:
        """Reserve bundles across nodes. The reference does 2-phase
        Prepare/Commit across raylets (gcs_placement_group_scheduler.h:265);
        with virtual nodes under one lock, prepare+commit is atomic."""
        with self._lock:
            alive = [self._nodes[n] for n in self._node_order]
            if not alive:
                raise PlacementGroupError("No alive nodes.")
            placed = self._place_bundles(bundles, strategy, alive)
            if placed is None:
                raise PlacementGroupError(
                    f"Placement group bundles {bundles} cannot be reserved "
                    f"with strategy {strategy} on the current cluster "
                    f"(nodes: {[dict(s.local.available) for s in alive]}).")
            pg_bundles = []
            for node_state, bundle_resources in placed:
                node_state.local.force_acquire(bundle_resources)
                pg_bundles.append(
                    _PGBundle(node_state.node_id, bundle_resources))
            self._pgs[pg_id] = _PlacementGroup(pg_id, strategy, pg_bundles)

    def _place_bundles(self, bundles: List[Dict[str, float]], strategy: str,
                       alive: List[NodeState]):
        """Dry-run bundle→node assignment. Returns [(NodeState, bundle)] or
        None if infeasible. Mutates nothing."""
        shadow = {s.node_id: dict(s.local.available) for s in alive}

        def fits(node_id, need):
            return _fits(shadow[node_id], need)

        def take(node_id, need):
            for k, v in need.items():
                shadow[node_id][k] = shadow[node_id].get(k, 0.0) - v

        placed: List[Tuple[NodeState, Dict[str, float]]] = []
        if strategy == "STRICT_PACK":
            for state in alive:
                if all(_fits_cumulative(shadow[state.node_id], bundles)):
                    for b in bundles:
                        take(state.node_id, b)
                        placed.append((state, b))
                    return placed
            return None
        if strategy == "STRICT_SPREAD":
            if len(bundles) > len(alive):
                return None
            used = set()
            for b in bundles:
                chosen = None
                for state in sorted(alive, key=lambda s: s.utilization()):
                    if state.node_id in used:
                        continue
                    if fits(state.node_id, b):
                        chosen = state
                        break
                if chosen is None:
                    return None
                used.add(chosen.node_id)
                take(chosen.node_id, b)
                placed.append((chosen, b))
            return placed
        if strategy == "SPREAD":
            for i, b in enumerate(bundles):
                ranked = sorted(alive, key=lambda s: s.utilization())
                chosen = None
                for state in ranked[i % len(ranked):] + ranked[:i % len(ranked)]:
                    if fits(state.node_id, b):
                        chosen = state
                        break
                if chosen is None:
                    return None
                take(chosen.node_id, b)
                placed.append((chosen, b))
            return placed
        # PACK (default): fewest nodes — first-fit in node order.
        for b in bundles:
            chosen = None
            for state in alive:
                if fits(state.node_id, b):
                    chosen = state
                    break
            if chosen is None:
                return None
            take(chosen.node_id, b)
            placed.append((chosen, b))
        return placed

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            pg = self._pgs.pop(pg_id, None)
            if pg is None:
                return
            for b in pg.bundles:
                node = self._nodes.get(b.node_id)
                if node is not None and node.alive:
                    node.local.release(b.resources)

    def placement_group_exists(self, pg_id: PlacementGroupID) -> bool:
        with self._lock:
            return pg_id in self._pgs

    def placement_groups(self) -> Dict[PlacementGroupID, List[Dict[str, float]]]:
        with self._lock:
            return {pg_id: [dict(b.resources) for b in pg.bundles]
                    for pg_id, pg in self._pgs.items()}

    def placement_group_table(self) -> List[dict]:
        with self._lock:
            return [{
                "placement_group_id": pg_id.hex(),
                "strategy": pg.strategy,
                "bundles": [
                    {"node_id": b.node_id.hex(), "resources": dict(b.resources)}
                    for b in pg.bundles],
            } for pg_id, pg in self._pgs.items()]

    def reschedule_lost_bundles(self) -> List[PlacementGroupID]:
        """Re-reserve PG bundles whose node is no longer alive (the
        reference's PG rescheduling on node failure). Called on node death
        AND on node addition, so a bundle that couldn't be re-placed at
        death time lands as soon as capacity appears. Returns PGs touched."""
        touched = []
        with self._lock:
            for pg in self._pgs.values():
                for b in pg.bundles:
                    home = self._nodes.get(b.node_id)
                    if home is not None and home.alive:
                        continue
                    touched.append(pg.pg_id)
                    for state in (self._nodes[n] for n in self._node_order):
                        if state.local.try_acquire(b.resources) is not None:
                            b.node_id = state.node_id
                            b.available = dict(b.resources)
                            break
        return touched

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "total": self.total,
                "available": self.available,
                "num_nodes": len(self._node_order),
                "num_placement_groups": len(self._pgs),
            }

    def record_metrics(self) -> None:
        """Refresh cluster-level gauges (called by the head's metrics-
        agent collector before each export snapshot)."""
        from ray_tpu._private import builtin_metrics
        with self._lock:
            alive = len(self._node_order)
        builtin_metrics.alive_nodes().set(alive)


def make_cluster_scheduler(use_native: bool = True):
    """Native C++ engine (src/ray_tpu_native/sched.cc) when it builds;
    this pure-Python implementation otherwise. Both expose identical
    semantics (tests/test_native_sched.py asserts decision parity).
    ``use_native=False`` (the use_native_scheduler config flag) forces the
    Python engine; the RAY_TPU_NATIVE_SCHED=0 env var also disables."""
    try:
        from ray_tpu._private.native_sched import (
            NativeClusterResourceScheduler, native_sched_available)
        if use_native and native_sched_available():
            return NativeClusterResourceScheduler()
    except Exception:  # noqa: BLE001 - any native failure → Python engine
        pass
    return ClusterResourceScheduler()


def _fits_cumulative(avail: Dict[str, float], bundles: List[Dict[str, float]]):
    remaining = dict(avail)
    for b in bundles:
        ok = _fits(remaining, b)
        yield ok
        if not ok:
            return
        for k, v in b.items():
            remaining[k] = remaining.get(k, 0.0) - v
