"""Connected runtime for daemon/worker-side user code.

This kills the split-brain: user code executing on a node daemon (or in a
worker subprocess) used to auto-initialize a fresh, isolated local runtime —
nested ``.remote()`` calls ran in a private universe, head-created named
actors were invisible, and nested work escaped the head's resource
accounting. In the reference, every worker process embeds a CoreWorker wired
to the GCS/raylet, so tasks submit from anywhere
(/root/reference/src/ray/core_worker/core_worker.cc:1762), named actors
resolve anywhere
(/root/reference/src/ray/gcs/gcs_server/gcs_actor_manager.cc:241), and
references are owned/borrowed across processes
(/root/reference/src/ray/core_worker/reference_count.h:61).

Here the same composition property comes from a **client runtime**: when the
``ray_tpu`` API is touched from a daemon/worker execution context, the
process binds a :class:`ClientRuntime` whose operations are served by the
head over one multiplexed TCP connection (a second connection type on the
head's registration listener). The API layer (remote_function.py, actor.py)
is unchanged — it builds TaskSpecs exactly as on the head; the specs ship
pickled, and the head **re-mints task ids** before submission so ID
uniqueness stays a single-process property (the client's 4-byte unique
counter could otherwise birthday-collide with the head's).

Ownership: the head stays owner-of-record for every object. Each client
session holds head-side ObjectRef handles ("pins") for (a) refs it returned
to the client and (b) refs the client reported borrowing (``ref_add``
notices, sent when client code deserializes a ref from a payload); pins drop
on ``ref_del`` notices and wholesale on session death — a dying daemon
releases everything it borrowed.

Deadlock avoidance: a client ``get`` that blocks inside a running task ships
the task's id; the head releases that task's resources while the get blocks
and force-reacquires after (the client-side analog of the reference worker's
NotifyDirectCallTaskBlocked → raylet resource release).
"""

from __future__ import annotations

import collections
import contextlib
import hashlib
import logging
import os
import socket
import threading
import traceback
import types
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import serialization
from ray_tpu._private import wire as _wire
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID,
                                  PlacementGroupID, TaskID)
from ray_tpu._private.multinode import (_dumps, _loads, _recv_frame,
                                        _send_frame_best_effort,
                                        _send_frame)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.task_spec import TaskKind

logger = logging.getLogger("ray_tpu")


class HeadConnectionLost(ConnectionError):
    """The client runtime's head connection dropped mid-operation."""


# ---------------------------------------------------------------------------
# Client side
# ---------------------------------------------------------------------------


class _Waiter:
    __slots__ = ("event", "reply")

    def __init__(self):
        self.event = threading.Event()
        self.reply: Optional[dict] = None


class ClientConnection:
    """One multiplexed request/reply connection to the head (the client
    half of the protocol ClientSession serves)."""

    def __init__(self, address: Tuple[str, int]):
        self.address = tuple(address)
        self._sock = socket.create_connection(self.address, timeout=15)
        self._sock.settimeout(None)
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._send_lock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, _Waiter] = {}
        self._counter = 0
        self.closed = False
        _send_frame(self._sock, _dumps({"type": "client_runtime",
                                        "protocol": _wire.PROTOCOL_VERSION,
                                        "pid": os.getpid()}),
                    self._send_lock)
        self.hello = _loads(_recv_frame(self._sock))
        if self.hello.get("type") == "register_rejected":
            with contextlib.suppress(OSError):
                self._sock.close()  # no recv thread exists to close it
            raise _wire.ProtocolMismatch(self.hello["error"])
        assert self.hello.get("type") == "client_registered", self.hello
        self._recv_thread = threading.Thread(
            target=self._recv_loop, name="ray_tpu-client-recv", daemon=True)
        self._recv_thread.start()

    def _recv_loop(self) -> None:
        try:
            while True:
                reply = _loads(_recv_frame(self._sock))
                with self._lock:
                    waiter = self._pending.pop(reply.get("req_id"), None)
                if waiter is not None:
                    waiter.reply = reply
                    waiter.event.set()
                del waiter, reply
        except (ConnectionError, OSError):
            pass
        finally:
            self.close()

    def request(self, msg: dict, timeout: Optional[float] = None) -> dict:
        with self._lock:
            if self.closed:
                raise HeadConnectionLost(
                    f"head {self.address} connection is closed")
            self._counter += 1
            req_id = self._counter
            waiter = _Waiter()
            self._pending[req_id] = waiter
        msg["req_id"] = req_id
        payload = _dumps(msg)
        try:
            _send_frame(self._sock, payload, self._send_lock)
        except OSError as exc:
            with self._lock:
                self._pending.pop(req_id, None)
            raise HeadConnectionLost(
                f"send to head {self.address} failed: {exc}") from exc
        if not waiter.event.wait(timeout):
            with self._lock:
                self._pending.pop(req_id, None)
            raise TimeoutError(
                f"head did not reply to {msg.get('op')} within {timeout}s")
        reply = waiter.reply
        if reply is None or reply.get("type") == "closed":
            raise HeadConnectionLost(
                f"head {self.address} connection dropped while "
                f"{msg.get('op')} was in flight")
        if not reply.get("ok", True):
            exc, remote_tb = _loads(reply["error"])
            raise exc
        return reply

    def notify(self, msg: dict) -> None:
        """Fire-and-forget (req_id 0: the session handles it inline and
        never replies)."""
        msg["req_id"] = 0
        # connection gone => session death drops the pins anyway
        _send_frame_best_effort(self._sock, _dumps(msg), self._send_lock)

    def close(self) -> None:
        with self._lock:
            if self.closed:
                return
            self.closed = True
            pending = list(self._pending.values())
            self._pending.clear()
        for waiter in pending:
            waiter.reply = {"type": "closed"}
            waiter.event.set()
        try:
            self._sock.close()
        except OSError:
            pass


class _ClientRefs:
    """Client-side local reference counts + head pin notices. Mutations
    from ``__del__`` (any thread, any allocation point) only enqueue; a
    flusher thread ships ordered ref_add/ref_del notices."""

    def __init__(self, enqueue):
        self._lock = threading.Lock()
        self._counts: Dict[ObjectID, int] = {}
        self._pinned: set = set()
        self._enqueue = enqueue

    def mark_pinned(self, oid: ObjectID) -> None:
        """The head already pinned this oid for us (it arrived as an API
        return) — no ref_add notice needed for the first handle."""
        with self._lock:
            self._pinned.add(oid)

    def add_local(self, oid: ObjectID) -> None:
        with self._lock:
            self._counts[oid] = self._counts.get(oid, 0) + 1
            if oid in self._pinned:
                return
            self._pinned.add(oid)
        self._enqueue(("ref_add", oid.hex()))

    def on_deleted(self, oid: ObjectID) -> None:
        with self._lock:
            c = self._counts.get(oid, 0) - 1
            if c > 0:
                self._counts[oid] = c
                return
            self._counts.pop(oid, None)
            if oid not in self._pinned:
                return
            self._pinned.discard(oid)
        self._enqueue(("ref_del", oid.hex()))

    # Runtime.refs API compatibility for paths that check liveness.
    def has(self, oid: ObjectID) -> bool:
        with self._lock:
            return oid in self._counts


class _ClientFunctions:
    """Function table proxy: exports ship to the head's FunctionTable
    (reference: function export to GCS KV); loads fetch bytes back."""

    def __init__(self, conn: ClientConnection):
        self._conn = conn
        self._lock = threading.Lock()
        self._by_id: Dict[bytes, bytes] = {}
        self._loaded: Dict[bytes, Any] = {}
        self._shipped: set = set()

    def export(self, fn) -> bytes:
        try:
            payload = serialization.dumps_function(fn)
        except Exception as exc:  # noqa: BLE001
            raise ValueError(
                "This function/class captured objects that cannot be "
                "serialized, so it cannot be submitted from a remote "
                "worker context (the head must receive its bytes). Make "
                f"it importable/picklable. Underlying error: {exc}")
        fn_id = self.export_bytes(payload)
        with self._lock:
            self._loaded.setdefault(fn_id, fn)
        return fn_id

    def export_bytes(self, payload: bytes) -> bytes:
        fn_id = hashlib.sha1(payload).digest()
        with self._lock:
            known = fn_id in self._shipped
            self._by_id.setdefault(fn_id, payload)
        if not known:
            self._conn.request({"op": "reg_fn", "payload": payload})
            with self._lock:
                self._shipped.add(fn_id)
        return fn_id

    def get_bytes(self, fn_id: bytes) -> bytes:
        with self._lock:
            payload = self._by_id.get(fn_id)
        if payload is not None:
            return payload
        reply = self._conn.request({"op": "fn_bytes", "fn_id": fn_id})
        payload = reply.get("payload")
        if payload is None:
            raise KeyError(fn_id)
        with self._lock:
            self._by_id[fn_id] = payload
            self._shipped.add(fn_id)
        return payload

    def load(self, fn_id: bytes):
        with self._lock:
            fn = self._loaded.get(fn_id)
        if fn is not None:
            return fn
        fn = serialization.loads_function(self.get_bytes(fn_id))
        with self._lock:
            self._loaded[fn_id] = fn
        return fn


class _ClientStore:
    def __init__(self, conn: ClientConnection):
        self._conn = conn

    def contains(self, oid: ObjectID) -> bool:
        return bool(self._conn.request(
            {"op": "contains", "ref": oid.hex()})["contains"])


class _ClientScheduler:
    def __init__(self, conn: ClientConnection):
        self._conn = conn

    def nodes_snapshot(self) -> List[dict]:
        return self._conn.request({"op": "nodes"})["nodes"]

    def placement_group_exists(self, pg_id: PlacementGroupID) -> bool:
        return bool(self._conn.request(
            {"op": "pg_exists", "pg_id": pg_id.hex()})["exists"])


def _attached_arena():
    """The shm arena this WORKER process shares with its daemon (None
    outside worker-subprocess contexts or when the arena is gone)."""
    try:
        from ray_tpu._private import worker_process as wp
        executor = getattr(wp, "_current_executor", None)
        if executor is not None:
            return executor._get_arena()
    except Exception:  # noqa: BLE001 - arena optional
        pass
    return None


def _local_object_addr(daemon=None) -> Optional[Tuple[str, int]]:
    """This context's object-server address — the OWNER endpoint stamped
    into owner hints (phase 3). In-daemon: the daemon's server; worker
    subprocess: the daemon's server via RAY_TPU_OBJECT_ADDR."""
    if daemon is not None and daemon._object_server is not None and \
            daemon._object_server_host:
        return (daemon._object_server_host, daemon._object_server.port)
    raw = os.environ.get("RAY_TPU_OBJECT_ADDR")
    if raw and ":" in raw:
        host, _, port = raw.rpartition(":")
        try:
            return (host, int(port))
        except ValueError:
            return None
    return None


class ClientRuntime:
    """Head-connected runtime bound by worker.py when user code runs in a
    daemon/worker context. Implements the Runtime surface the API layer
    uses; every operation is served by the head's ClientSession."""

    is_client = True

    def __init__(self, address: Tuple[str, int]):
        self._conn = ClientConnection(address)
        hello = self._conn.hello
        self.job_id = JobID(bytes.fromhex(hello["job_id"]))
        self.session_id = hello["session_id"]
        self.namespace = hello.get("namespace", "default")
        self.head_node_id = NodeID(bytes.fromhex(hello["head_node_id"]))
        self.node_resources = types.SimpleNamespace(
            num_cpus=hello.get("num_cpus", 0),
            num_tpus=hello.get("num_tpus", 0))
        self.functions = _ClientFunctions(self._conn)
        self.store = _ClientStore(self._conn)
        self.scheduler = _ClientScheduler(self._conn)
        self.refs = _ClientRefs(self._enqueue_notice)
        self._actor_info: Dict[ActorID, dict] = {}
        self._actor_info_lock = threading.Lock()
        # Node-resident put threshold: payloads at/above it stay in the
        # creating node's table (same knob the head uses to decide
        # inline vs daemon-resident results). Local config defaults —
        # per-head _system_config overrides do not travel here, which
        # only shifts the inline/local cutover, never correctness.
        from ray_tpu._private.ray_config import make_ray_config
        self._put_local_limit = int(
            make_ray_config(None).remote_object_inline_limit_bytes
            or (1 << 20))
        # Owner-ward resolutions served without a head op (phase 3;
        # tests assert this moves while head op counters stand still).
        self.ownerward_gets = 0
        # Ordered ref-notice queue + flusher (see _ClientRefs).
        self._notices: "collections.deque" = collections.deque()
        self._notice_event = threading.Event()
        self._flusher = threading.Thread(
            target=self._flush_loop, name="ray_tpu-client-refgc",
            daemon=True)
        self._flusher.start()

    # -- plumbing -------------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._conn.closed

    def _enqueue_notice(self, notice: Tuple[str, str]) -> None:
        self._notices.append(notice)
        self._notice_event.set()

    def _flush_loop(self) -> None:
        while not self._conn.closed:
            self._notice_event.wait(timeout=0.2)
            self._notice_event.clear()
            while self._notices:
                try:
                    op, ref_hex = self._notices.popleft()
                except IndexError:
                    break
                self._conn.notify({"op": op, "ref": ref_hex})

    def _refs_from_hex(self, hexes: List[str]) -> List[ObjectRef]:
        refs = []
        for h in hexes:
            oid = ObjectID.from_hex(h)
            # The head pinned these for this session before replying; the
            # first local handle must not send a redundant ref_add.
            self.refs.mark_pinned(oid)
            refs.append(ObjectRef(oid))
        return refs

    @staticmethod
    def _current_task_id_hex() -> Optional[str]:
        from ray_tpu._private.runtime import current_task_spec
        spec = current_task_spec()
        if spec is None:
            return None
        hex_id = getattr(spec, "task_id_hex", None)
        if hex_id is not None:
            return hex_id
        task_id = getattr(spec, "task_id", None)
        return task_id.hex() if task_id is not None else None

    def on_ref_deleted(self, oid: ObjectID) -> None:
        self.refs.on_deleted(oid)

    # -- task/actor submission -----------------------------------------

    def register_function(self, fn) -> bytes:
        return self.functions.export(fn)

    def submit_task(self, spec) -> List[ObjectRef]:
        reply = self._conn.request(
            {"op": "submit_task", "spec": _dumps(spec)})
        return self._refs_from_hex(reply["refs"])

    def submit_actor_task(self, spec) -> List[ObjectRef]:
        reply = self._conn.request(
            {"op": "submit_actor_task", "spec": _dumps(spec)})
        return self._refs_from_hex(reply["refs"])

    def create_actor(self, spec, **options) -> ActorID:
        # Forward ALL options verbatim: the server applies them with
        # Runtime.create_actor(spec, **opts), so a kwarg added to the head
        # runtime (e.g. concurrency_groups) works from client contexts
        # without this class naming it — the two signatures cannot drift
        # and head-side defaults stay authoritative.
        reply = self._conn.request({
            "op": "create_actor", "spec": _dumps(spec), "opts": options})
        actor_id = ActorID(bytes.fromhex(reply["actor_id"]))
        with self._actor_info_lock:
            self._actor_info[actor_id] = {
                "exists": True, "fn_id": spec.function_id,
                "name": options.get("name", ""),
                "namespace": options.get("namespace", "default"),
                "class_name": (spec.name or "").rsplit(".", 1)[0],
                "dead": False, "num_restarts": 0,
            }
        return actor_id

    def _fetch_actor_info(self, actor_id: ActorID) -> dict:
        reply = self._conn.request(
            {"op": "actor_info", "actor_id": actor_id.hex()})
        info = {"exists": reply["exists"], "fn_id": reply.get("fn_id"),
                "name": reply.get("name", ""),
                "namespace": reply.get("namespace", "default"),
                "class_name": reply.get("class_name", ""),
                "dead": reply.get("dead", False),
                "num_restarts": reply.get("num_restarts", 0)}
        if info["exists"]:
            with self._actor_info_lock:
                self._actor_info[actor_id] = info
        return info

    def actor_state(self, actor_id: ActorID):
        with self._actor_info_lock:
            info = self._actor_info.get(actor_id)
        if info is None:
            info = self._fetch_actor_info(actor_id)
        if not info["exists"]:
            return None
        return types.SimpleNamespace(
            actor_id=actor_id,
            creation_spec=types.SimpleNamespace(
                function_id=info["fn_id"], _tpu_ids=None, _node_id=None),
            dead=info["dead"], name=info["name"],
            namespace=info["namespace"],
            class_name=info.get("class_name", ""),
            num_restarts=info["num_restarts"])

    def get_named_actor(self, name: str,
                        namespace: str = "default") -> ActorID:
        reply = self._conn.request(
            {"op": "get_named_actor", "name": name, "namespace": namespace})
        return ActorID(bytes.fromhex(reply["actor_id"]))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self._conn.request({"op": "kill_actor", "actor_id": actor_id.hex(),
                            "no_restart": no_restart})

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        self._conn.request({"op": "cancel", "ref": ref.hex(),
                            "force": force})

    # -- objects --------------------------------------------------------

    def put(self, value: Any) -> ObjectRef:
        payload = serialization.serialize(value)
        ref = self._put_node_resident(payload)
        if ref is not None:
            return ref
        reply = self._conn.request({"op": "put", "payload": payload})
        return self._refs_from_hex([reply["ref"]])[0]

    def _put_node_resident(self, payload: bytes) -> Optional[ObjectRef]:
        """Distributed-ownership put (reference: owner-is-creator,
        reference_count.h:61): a big payload created on a node STAYS in
        that node's object table — only a directory registration goes to
        the head, and readers pull the bytes over the node-to-node data
        plane. In-daemon contexts write the daemon table directly;
        worker subprocesses write the shared shm arena and the daemon
        ADOPTS the entry (bookkeeping) during registration. Returns
        None when this context cannot (or should not: small payloads
        ship inline) keep the bytes local — caller falls back to the
        head-stored put."""
        if len(payload) < self._put_local_limit:
            return None
        import uuid

        from ray_tpu._private import multinode as mn
        key = f"cput-{uuid.uuid4().hex}"
        daemon = mn._current_daemon
        adopt = False
        node_hex = None
        arena = None
        if daemon is not None and daemon.node_id_hex:
            daemon._table.put(key, payload)
            node_hex = daemon.node_id_hex
        else:
            node_hex = os.environ.get("RAY_TPU_NODE_ID")
            arena = _attached_arena()
            if not node_hex or arena is None or \
                    not arena.put_bytes(key, payload):
                return None  # no local store (thin client / arena full)
            adopt = True
        try:
            reply = self._conn.request({
                "op": "put_remote", "node": node_hex, "key": key,
                "size": len(payload), "adopt": adopt})
        except Exception:  # noqa: BLE001 - registration failed: clean up
            logger.exception("node-resident put registration failed; "
                             "falling back to head-stored put")
            # BOTH stores must release the orphan: an unadopted arena
            # entry has no bookkeeping — nothing would ever free it.
            if daemon is not None:
                daemon._table.free(key)
            elif arena is not None:
                arena.delete(key)
            return None
        # Owner hint (phase 3): the creator knows the owner — itself.
        # Any borrower of this ref can then locate/fetch/register
        # straight against this node's object server, no head op.
        hint = None
        addr = _local_object_addr(daemon)
        if addr is not None and node_hex:
            hint = (key, addr[0], addr[1], node_hex)
        oid = ObjectID.from_hex(reply["ref"])
        # Pin BEFORE constructing (as _refs_from_hex does): the head
        # pinned this ref before replying; the first local handle must
        # not send a redundant ref_add.
        self.refs.mark_pinned(oid)
        return ObjectRef(oid, owner_hint=hint)

    #: sentinel: owner-ward resolution missed, fall back to the head.
    _MISS = object()

    def get(self, refs: List[ObjectRef],
            timeout: Optional[float]) -> List[Any]:
        # Phase-3 fast path: refs carrying an owner hint resolve
        # against the OWNER's object server (local read on the owner
        # node, direct pull elsewhere) — the head is not involved.
        # Anything unhinted, freed, or owner-dead falls back to the
        # head op (which waits / reconstructs as before).
        values: List[Any] = [None] * len(refs)
        remaining: List[Tuple[int, ObjectRef]] = []
        for i, r in enumerate(refs):
            hint = getattr(r, "_owner_hint", None)
            v = (self._get_ownerward(hint, timeout)
                 if hint else self._MISS)
            if v is self._MISS:
                remaining.append((i, r))
            else:
                values[i] = v
        if remaining:
            reply = self._conn.request({
                "op": "get",
                "refs": [r.hex() for _i, r in remaining],
                "timeout": timeout,
                "holding_task": self._current_task_id_hex(),
            })
            for (i, _r), v in zip(remaining, _loads(reply["values"])):
                values[i] = v
        return values

    def _get_ownerward(self, hint, timeout: Optional[float]) -> Any:
        """Resolve one hinted ref owner-ward; _MISS on any failure.
        Network waits are capped by the CALLER's timeout (a get with
        timeout=0.5 on a dead owner must miss fast and let the head
        fallback apply the real deadline — never serve 30s of connect
        retries first)."""
        from ray_tpu._private import multinode as mn
        from ray_tpu._private.dataplane import (ObjectPullError,
                                                fetch_remote_bytes,
                                                pull_object)
        try:
            key, host, port, node_hex = hint
        except (TypeError, ValueError):
            return self._MISS
        net_timeout = 10.0 if timeout is None else max(
            0.1, min(10.0, timeout))
        payload = None
        try:
            daemon = mn._current_daemon
            if daemon is not None:
                if daemon.node_id_hex != node_hex and \
                        not daemon._table.contains(key):
                    # Peer-owned: pull into this node's table (cached
                    # for siblings, admission-bounded) then read local.
                    pull_object((host, port), key, daemon._table,
                                timeout=net_timeout, retries=0)
                with daemon._table.pinned(key) as raw:
                    if raw is not None:
                        payload = bytes(raw)
            else:
                arena = _attached_arena()
                if arena is not None and \
                        os.environ.get("RAY_TPU_NODE_ID") == node_hex:
                    view = arena.get_bytes(key)
                    if view is not None:
                        try:
                            payload = bytes(view)
                        finally:
                            with contextlib.suppress(BufferError):
                                view.release()
                            # get_bytes holds an arena refcount the
                            # caller must return (native_store
                            # contract) — a leaked pin would make the
                            # eventual free fail forever.
                            with contextlib.suppress(Exception):
                                arena.release(key)
                if payload is None:
                    payload = fetch_remote_bytes((host, port), key,
                                                 timeout=net_timeout)
        except (ObjectPullError, OSError, ConnectionError):
            return self._MISS
        if payload is None:
            return self._MISS
        self.ownerward_gets += 1
        return serialization.deserialize(payload)

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        reply = self._conn.request({
            "op": "wait", "refs": [r.hex() for r in refs],
            "num_returns": num_returns, "timeout": timeout,
        })
        by_hex = {r.hex(): r for r in refs}
        return ([by_hex[h] for h in reply["ready"]],
                [by_hex[h] for h in reply["pending"]])

    def free_objects(self, oids: List[ObjectID]) -> None:
        self._conn.request(
            {"op": "free", "refs": [oid.hex() for oid in oids]})

    # -- cluster introspection / PGs / KV -------------------------------

    def cluster_resources(self) -> Dict[str, float]:
        return self._conn.request({"op": "cluster_resources"})["resources"]

    def available_resources(self) -> Dict[str, float]:
        return self._conn.request(
            {"op": "available_resources"})["resources"]

    def task_events(self) -> List[dict]:
        return self._conn.request({"op": "task_events"})["events"]

    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str = "PACK",
                               name: str = "") -> PlacementGroupID:
        reply = self._conn.request({"op": "create_pg", "bundles": bundles,
                                    "strategy": strategy, "name": name})
        return PlacementGroupID(bytes.fromhex(reply["pg_id"]))

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        self._conn.request({"op": "remove_pg", "pg_id": pg_id.hex()})

    def kv_put(self, namespace: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        return self._conn.request(
            {"op": "kv_put", "ns": namespace, "key": key, "value": value,
             "overwrite": overwrite})["existed"]

    def kv_get(self, namespace: str, key: bytes):
        return self._conn.request(
            {"op": "kv_get", "ns": namespace, "key": key})["value"]

    def kv_del(self, namespace: str, key: bytes) -> bool:
        return self._conn.request(
            {"op": "kv_del", "ns": namespace, "key": key})["deleted"]

    def kv_keys(self, namespace: str, prefix: bytes = b"") -> list:
        return self._conn.request(
            {"op": "kv_keys", "ns": namespace, "prefix": prefix})["keys"]

    # -- lifecycle ------------------------------------------------------

    def shutdown(self) -> None:
        self._conn.close()
        self._notice_event.set()


# ---------------------------------------------------------------------------
# Head side
# ---------------------------------------------------------------------------


class ClientSession:
    """Head-side server for one ClientRuntime connection: executes API
    ops against the real runtime and holds this session's object pins
    (head-side ObjectRef handles). Dies with the connection — a dead
    daemon's borrowed refs are released wholesale."""

    def __init__(self, runtime, sock: socket.socket, addr, on_close=None):
        self.runtime = runtime
        self._sock = sock
        self.addr = addr
        self._send_lock = threading.Lock()
        self._plock = threading.Lock()
        self._pinned: Dict[ObjectID, ObjectRef] = {}
        # Actors this session created (reference: ownership — a non-
        # detached actor dies with its creator). Reaped on close();
        # detached actors are never tracked here.
        self._created_actors: set = set()
        self._closed = False
        self._on_close = on_close

    def serve(self) -> None:
        try:
            while True:
                msg = _loads(_recv_frame(self._sock))
                if msg.get("req_id", 0) == 0:
                    self._handle_notice(msg)
                    continue
                # Per-request threads: get/wait block arbitrarily long and
                # must not stall the session's other requests.
                threading.Thread(
                    target=self._handle, args=(msg,),
                    name="ray_tpu-client-op", daemon=True).start()
                del msg
        except (ConnectionError, OSError):
            pass
        finally:
            self.close()

    def close(self) -> None:
        with self._plock:
            if self._closed:
                return
            self._closed = True
            self._pinned.clear()  # handles die → refcounts decrement
            created = list(self._created_actors)
            self._created_actors.clear()
        # Client disconnect reaps the actors this session created —
        # EXCEPT detached ones, whose lifetime the GCS owns (they were
        # never tracked). Double-check liveness/lifetime against the
        # runtime: a handle may have been killed or re-created since.
        for actor_id in created:
            state = self.runtime.actor_state(actor_id)
            if state is None or state.dead or state.detached:
                continue
            try:
                self.runtime.kill_actor(actor_id, no_restart=True)
            except Exception:  # noqa: BLE001 - teardown best effort
                logger.exception("failed to reap client actor %s",
                                 actor_id.hex()[:12])
        try:
            self._sock.close()
        except OSError:
            pass
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:  # noqa: BLE001 - teardown best effort
                pass

    # -- pins -----------------------------------------------------------

    def _pin(self, refs: List[ObjectRef]) -> None:
        with self._plock:
            if self._closed:
                return
            for r in refs:
                self._pinned[r.object_id()] = r

    def _handle_notice(self, msg: dict) -> None:
        op = msg.get("op")
        try:
            if op == "ref_add":
                oid = ObjectID.from_hex(msg["ref"])
                self._pin([ObjectRef(oid)])
            elif op == "ref_del":
                with self._plock:
                    self._pinned.pop(ObjectID.from_hex(msg["ref"]), None)
        except Exception:  # noqa: BLE001 - notices are best-effort
            logger.exception("client-session notice %s failed", op)

    # -- request dispatch ----------------------------------------------

    def _handle(self, msg: dict) -> None:
        req_id = msg.get("req_id")
        try:
            reply = self._dispatch(msg)
            reply["req_id"] = req_id
            reply.setdefault("ok", True)
        except BaseException as exc:  # noqa: BLE001 - ship to client
            try:
                payload = _dumps((exc, traceback.format_exc()))
            except Exception:  # noqa: BLE001 - unpicklable exception
                payload = _dumps((RuntimeError(
                    f"{type(exc).__name__}: {exc}"),
                    traceback.format_exc()))
            reply = {"req_id": req_id, "ok": False, "error": payload}
        # client gone => close() runs from the serve loop
        _send_frame_best_effort(self._sock, _dumps(reply), self._send_lock)

    def _dispatch(self, msg: dict) -> dict:
        # Schema check BEFORE dispatch (wire.py CLIENT_SCHEMAS): a
        # drifted op fails with the exact field name as a normal error
        # reply, never a KeyError inside a handler.
        _wire.validate_client_op(msg)
        op = msg["op"]
        from ray_tpu._private.event_stats import GLOBAL
        with GLOBAL.timed(f"client.{op}"):
            return self._dispatch_op(op, msg)

    def _dispatch_op(self, op: str, msg: dict) -> dict:
        rt = self.runtime
        if op == "submit_task":
            spec = _loads(msg["spec"])
            # Re-mint: task-id uniqueness is a single-process (head)
            # property; a client-minted id could collide with the head's
            # own counter (ids.py _task_unique birthday note).
            spec.task_id = TaskID.for_normal_task(rt.job_id)
            refs = rt.submit_task(spec)
            self._pin(refs)
            return {"refs": [r.hex() for r in refs]}
        if op == "submit_actor_task":
            spec = _loads(msg["spec"])
            spec.task_id = TaskID.for_actor_task(spec.actor_id)
            refs = rt.submit_actor_task(spec)
            self._pin(refs)
            return {"refs": [r.hex() for r in refs]}
        if op == "create_actor":
            spec = _loads(msg["spec"])
            opts = msg["opts"]
            # get_if_exists may hand back an actor some OTHER session
            # (or the head driver) created — this session must not adopt
            # its lifetime. Resolve the name first to tell apart.
            existing = None
            if opts.get("name") and opts.get("get_if_exists"):
                try:
                    existing = rt.get_named_actor(
                        opts["name"], opts.get("namespace") or "default")
                except ValueError:
                    existing = None
            # No re-mint needed: creation task ids derive deterministically
            # from the actor id (TaskID.for_actor_creation — 8 random
            # actor bytes, zero unique part), a shape head-minted normal/
            # actor task ids can never take.
            actor_id = rt.create_actor(spec, **opts)
            if actor_id != existing and opts.get("lifetime") != "detached":
                with self._plock:
                    if not self._closed:
                        self._created_actors.add(actor_id)
            return {"actor_id": actor_id.hex()}
        if op == "actor_info":
            state = rt.actor_state(ActorID(bytes.fromhex(msg["actor_id"])))
            if state is None:
                return {"exists": False}
            return {"exists": True,
                    "fn_id": state.creation_spec.function_id,
                    "name": state.name, "namespace": state.namespace,
                    "class_name": getattr(state, "class_name", ""),
                    "dead": state.dead,
                    "num_restarts": state.num_restarts,
                    "lifetime": state.lifetime}
        if op == "get_named_actor":
            actor_id = rt.get_named_actor(msg["name"], msg["namespace"])
            return {"actor_id": actor_id.hex()}
        if op == "kill_actor":
            rt.kill_actor(ActorID(bytes.fromhex(msg["actor_id"])),
                          msg["no_restart"])
            return {}
        if op == "cancel":
            rt.cancel(ObjectRef(ObjectID.from_hex(msg["ref"])),
                      msg["force"])
            return {}
        if op == "reg_fn":
            rt.functions.export_bytes(msg["payload"])
            return {}
        if op == "fn_bytes":
            try:
                return {"payload": rt.functions.get_bytes(msg["fn_id"])}
            except KeyError:
                return {"payload": None}
        if op == "put":
            ref = rt.put(serialization.deserialize(msg["payload"]))
            self._pin([ref])
            return {"ref": ref.hex()}
        if op == "put_remote":
            # Distributed-ownership put: bytes already live in the
            # creating node's table; register the directory entry only.
            ref = rt.register_remote_put(
                NodeID(bytes.fromhex(msg["node"])), msg["key"],
                int(msg["size"]), adopt=bool(msg.get("adopt")))
            self._pin([ref])
            return {"ref": ref.hex()}
        if op == "get":
            refs = [ObjectRef(ObjectID.from_hex(h)) for h in msg["refs"]]
            held = None
            if msg.get("holding_task"):
                held = rt.client_get_release(msg["holding_task"])
            try:
                values = rt.get(refs, msg.get("timeout"))
            finally:
                if held is not None:
                    rt.client_get_reacquire(held)
            return {"values": _dumps(values)}
        if op == "wait":
            refs = [ObjectRef(ObjectID.from_hex(h)) for h in msg["refs"]]
            ready, pending = rt.wait(refs, msg["num_returns"],
                                     msg.get("timeout"))
            return {"ready": [r.hex() for r in ready],
                    "pending": [r.hex() for r in pending]}
        if op == "contains":
            return {"contains": rt.store.contains(
                ObjectID.from_hex(msg["ref"]))}
        if op == "free":
            oids = [ObjectID.from_hex(h) for h in msg["refs"]]
            with self._plock:
                for oid in oids:
                    self._pinned.pop(oid, None)
            rt.free_objects(oids)
            return {}
        if op == "cluster_resources":
            return {"resources": rt.cluster_resources()}
        if op == "available_resources":
            return {"resources": rt.available_resources()}
        if op == "nodes":
            return {"nodes": rt.scheduler.nodes_snapshot()}
        if op == "pg_exists":
            return {"exists": rt.scheduler.placement_group_exists(
                PlacementGroupID(bytes.fromhex(msg["pg_id"])))}
        if op == "create_pg":
            pg_id = rt.create_placement_group(
                msg["bundles"], msg["strategy"], msg["name"])
            return {"pg_id": pg_id.hex()}
        if op == "remove_pg":
            rt.remove_placement_group(
                PlacementGroupID(bytes.fromhex(msg["pg_id"])))
            return {}
        if op == "task_events":
            return {"events": rt.task_events()}
        if op == "kv_put":
            return {"existed": rt.kv_put(msg["ns"], msg["key"],
                                         msg["value"], msg["overwrite"])}
        if op == "kv_get":
            return {"value": rt.kv_get(msg["ns"], msg["key"])}
        if op == "kv_del":
            return {"deleted": rt.kv_del(msg["ns"], msg["key"])}
        if op == "kv_keys":
            return {"keys": rt.kv_keys(msg["ns"], msg["prefix"])}
        if op == "ping":
            return {}
        raise ValueError(f"unknown client op {op!r}")
