"""Session log directories, per-process capture, and driver-side printing.

Analog of the reference's python/ray/_private/ray_logging/ package: every
session gets a directory under ``<tmpdir>/ray_tpu-sessions/session_<id>``
with a
``session_latest`` symlink, worker subprocess stdout/stderr are captured
to per-proc files inside it (``worker-<uuid>-<pid>.out/.err``), node
daemons route their own streams there too (``raylet-<pid>.out/.err``),
and the head's log monitor + the daemons' monitors stream new lines to
the driver with ``(name pid=, node=)`` prefixes (log_monitor.py carries
the tailing; this module owns paths, files, redirection, and the driver
printer).

Layout (shared across all processes of one session on a host)::

    <tmpdir>/ray_tpu-sessions/
        session_latest -> session_<id>          # most recent driver
        session_<id>/logs/
            head/worker-<uuid>-<pid>.out        # head-spawned workers
            node-<node_id12>/raylet-<pid>.err   # daemon's own stderr
            node-<node_id12>/worker-...         # daemon-spawned workers

Only the process that CREATED a capture file tails it (explicit
registration with its LogMonitor) — two daemons on one host share the
session dir but never double-stream each other's files.
"""

from __future__ import annotations

import logging
import os
import sys
import tempfile
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

#: Control line emitted by worker processes at task start so the tailer
#: can prefix subsequent output with the task's name (the reference gets
#: this via setproctitle; we ride the captured stream itself). Never
#: forwarded to the driver.
TASK_MARKER = "::ray_tpu::task::"

#: Env var that tells a worker subprocess its streams are captured (so
#: task markers are worth emitting; with inherited streams they would
#: pollute the user's console).
MARKER_ENV = "RAY_TPU_LOG_MARKERS"

_lock = threading.Lock()
_node_log_dir: Optional[str] = None      # this process's dir under logs/
_session_dir: Optional[str] = None
# New capture files are announced here so the process's LogMonitor can
# start tailing them: callback(path, proc_name, pid, source).
_capture_callback: Optional[Callable[[str, str, int, str], None]] = None


# ---------------------------------------------------------------------------
# Session directory management
# ---------------------------------------------------------------------------


def sessions_root() -> str:
    # "ray_tpu-sessions", NOT "ray_tpu": a plain /tmp/ray_tpu directory
    # would shadow the installed package as a namespace package for any
    # script whose cwd is the tmpdir (import ray_tpu -> empty module).
    return os.path.join(tempfile.gettempdir(), "ray_tpu-sessions")


def session_dir_for(session_id: str) -> str:
    return os.path.join(sessions_root(), f"session_{session_id}")


def setup_session(session_id: str, node_dirname: str) -> str:
    """Create (or join) the session's log tree and claim a per-node dir.
    Returns this process's log dir and records it process-globally so
    worker spawns capture into it. The head passes ``head``; daemons
    pass ``node-<node_id12>`` once registration hands them the session
    id. Also repoints the ``session_latest`` symlink (atomic rename, so
    a concurrent `ray-tpu logs` never sees a dangling link)."""
    global _node_log_dir, _session_dir
    sdir = session_dir_for(session_id)
    log_dir = os.path.join(sdir, "logs", node_dirname)
    os.makedirs(log_dir, exist_ok=True)
    link = os.path.join(sessions_root(), "session_latest")
    try:
        tmp_link = link + f".{os.getpid()}.{uuid.uuid4().hex[:6]}"
        os.symlink(os.path.basename(sdir), tmp_link)
        os.replace(tmp_link, link)
    except OSError:  # symlink-hostile filesystem: latest lookup degrades
        pass
    with _lock:
        _session_dir = sdir
        _node_log_dir = log_dir
    return log_dir


def clear_session() -> None:
    """Forget the process-global session (runtime shutdown): later worker
    spawns in this process fall back to inherited streams. The files
    stay on disk for `ray-tpu logs`."""
    global _node_log_dir, _session_dir, _capture_callback
    with _lock:
        _node_log_dir = None
        _session_dir = None
        _capture_callback = None


def current_log_dir() -> Optional[str]:
    return _node_log_dir


def current_session_dir() -> Optional[str]:
    return _session_dir


def latest_session_dir() -> Optional[str]:
    """Resolve ``session_latest`` WITHOUT initializing a runtime (the CLI
    must read the previous driver's logs, not create a fresh empty
    session)."""
    cur = _session_dir
    if cur is not None and os.path.isdir(cur):
        return cur
    link = os.path.join(sessions_root(), "session_latest")
    target = os.path.realpath(link)
    return target if os.path.isdir(target) else None


def register_capture_callback(
        cb: Optional[Callable[[str, str, int, str], None]]) -> None:
    """The process's LogMonitor hooks new capture files here."""
    global _capture_callback
    with _lock:
        _capture_callback = cb


def _announce(path: str, proc_name: str, pid: int, source: str) -> None:
    cb = _capture_callback
    if cb is not None:
        try:
            cb(path, proc_name, pid, source)
        except Exception:  # noqa: BLE001 - capture must not break spawns
            logger.exception("log capture callback failed")


# ---------------------------------------------------------------------------
# Worker subprocess capture (used by worker_process._spawn_worker)
# ---------------------------------------------------------------------------


class _WorkerCapture:
    """Open per-source capture files for one worker-to-be. ``finalize
    (pid)`` after Popen renames them to embed the real pid (the child's
    fds survive the rename) and registers them with the monitor;
    ``abort()`` on a failed spawn removes them. Container workers pass
    ``sources=("err",)`` — their stdout is the protocol pipe."""

    def __init__(self, log_dir: str, sources=("out", "err")):
        token = uuid.uuid4().hex[:10]
        self._base = os.path.join(log_dir, f"worker-{token}")
        # Append mode: rotation is copytruncate-style (log_monitor.py),
        # and O_APPEND writes land at the new EOF after a truncate.
        self._files = {source: open(f"{self._base}.{source}", "ab",
                                    buffering=0) for source in sources}
        self.out = self._files.get("out")
        self.err = self._files.get("err")

    def finalize(self, pid: int) -> None:
        paths = {}
        for source, f in self._files.items():
            final = f"{self._base}-{pid}.{source}"
            try:
                os.replace(f"{self._base}.{source}", final)
            except OSError:
                final = f"{self._base}.{source}"
            paths[source] = final
            f.close()  # the child owns the fd now
        for source, path in paths.items():
            _announce(path, "worker", pid, source)

    def abort(self) -> None:
        for source, f in self._files.items():
            f.close()
            try:
                os.unlink(f"{self._base}.{source}")
            except OSError:
                pass


def open_worker_capture(sources=("out", "err")) -> Optional[_WorkerCapture]:
    """Capture files for a worker spawn, or None when this process has
    no session log dir (standalone pool use): the spawn then inherits
    the parent's streams — never DEVNULL."""
    log_dir = _node_log_dir
    if log_dir is None:
        return None
    try:
        return _WorkerCapture(log_dir, sources)
    except OSError:
        logger.exception("could not open worker log files")
        return None


def open_launch_capture(tag: str) -> Tuple[Optional[Any], Optional[Any]]:
    """Capture files for a LAUNCHED daemon process (spark / autoscaler
    node providers): the daemon re-routes its own streams into the
    session dir once registered, so these only hold pre-registration
    output (import errors, argparse failures) — exactly the output that
    used to vanish into DEVNULL. Returns (out_file, err_file) or
    (None, None) when no session dir exists (streams inherit)."""
    log_dir = _node_log_dir
    if log_dir is None:
        return None, None
    token = uuid.uuid4().hex[:10]
    base = os.path.join(log_dir, f"{tag}-{token}")
    try:
        return (open(base + ".out", "ab", buffering=0),
                open(base + ".err", "ab", buffering=0))
    except OSError:
        logger.exception("could not open launch log files")
        return None, None


# ---------------------------------------------------------------------------
# Daemon self-capture (multinode.NodeDaemon after registration)
# ---------------------------------------------------------------------------


def redirect_process_streams(log_dir: str, proc_name: str = "raylet"
                             ) -> List[Tuple[str, str]]:
    """Point this process's stdout/stderr at per-proc files in the
    session dir (``raylet-<pid>.out/.err``) so in-daemon task prints and
    crash output are captured like worker output. A tty stream is left
    alone (interactive `ray-tpu start` keeps its console). Returns
    [(path, source)] for the streams actually redirected, for the
    caller to hand its LogMonitor."""
    redirected = []
    pid = os.getpid()
    for source, fd, py_stream in (("out", 1, sys.stdout),
                                  ("err", 2, sys.stderr)):
        try:
            if py_stream is not None and py_stream.isatty():
                continue
        except (ValueError, OSError):
            pass  # closed/odd stream: still safe to redirect the fd
        path = os.path.join(log_dir, f"{proc_name}-{pid}.{source}")
        try:
            f = open(path, "ab", buffering=0)
            os.dup2(f.fileno(), fd)
            f.close()
            # The dup2 swapped the fd under Python's buffered wrapper;
            # line buffering keeps task print() output streamable.
            if py_stream is not None:
                try:
                    py_stream.reconfigure(line_buffering=True)
                except (AttributeError, ValueError, OSError):
                    pass
            redirected.append((path, source))
        except OSError:
            logger.exception("could not redirect %s to %s", source, path)
    return redirected


def attach_file_logging(log_dir: str, proc_name: str = "raylet") -> None:
    """Move this process's python logging onto a structured file handler
    (``raylet-<pid>.log`` — deliberately NOT tailed to the driver: a
    daemon's routine INFO stream is session-dir observability, not
    driver console traffic). Existing stream handlers are dropped so
    the captured .err file carries only genuine stderr output."""
    path = os.path.join(log_dir, f"{proc_name}-{os.getpid()}.log")
    try:
        handler = logging.FileHandler(path)
    except OSError:
        return
    handler.setFormatter(logging.Formatter(
        "%(asctime)s\t%(levelname)s\t%(name)s\t%(message)s"))
    root = logging.getLogger()
    for h in list(root.handlers):
        if isinstance(h, logging.StreamHandler) and \
                not isinstance(h, logging.FileHandler):
            root.removeHandler(h)
    root.addHandler(handler)
    if root.level == logging.NOTSET or root.level > logging.INFO:
        root.setLevel(logging.INFO)


# ---------------------------------------------------------------------------
# Task markers (worker side)
# ---------------------------------------------------------------------------


def markers_enabled() -> bool:
    return os.environ.get(MARKER_ENV) == "1"


def emit_task_marker(task_name: str) -> None:
    """Announce the current task on both captured streams so the tailer
    prefixes subsequent lines with its name. One line, consumed by
    LogMonitor, never forwarded."""
    line = f"{TASK_MARKER}{task_name}\n"
    for stream in (sys.stdout, sys.stderr):
        try:
            stream.write(line)
            stream.flush()
        except (ValueError, OSError):
            pass


# ---------------------------------------------------------------------------
# Driver-side formatting + printer
# ---------------------------------------------------------------------------

_COLOR_RESET = "\033[0m"
#: Prefix color by origin (reference: worker output cyan, raylet-ish
#: system processes yellow, stderr red) — applied only on a tty.
_COLORS = {("worker", "out"): "\033[36m",
           ("worker", "err"): "\033[31m",
           ("raylet", "out"): "\033[33m",
           ("raylet", "err"): "\033[31m"}


def format_log_batch(batch: Dict[str, Any], color: bool) -> List[str]:
    """Render one published batch into driver-console lines:
    ``(name pid=<pid>, node=<node12>) line``."""
    name = batch.get("task_name") or batch.get("proc_name") or "worker"
    node = (batch.get("node") or "")[:12]
    prefix = f"({name} pid={batch.get('pid')}, node={node})"
    if color:
        c = _COLORS.get((batch.get("proc_name", "worker"),
                         batch.get("source", "out")), "\033[36m")
        prefix = f"{c}{prefix}{_COLOR_RESET}"
    return [f"{prefix} {line}" for line in batch.get("lines", [])]


class DriverLogPrinter:
    """Subscribes to the runtime's ``logs`` pubsub channel and prints
    every streamed line to the driver's stdout (``init(log_to_driver=
    False)`` simply never starts one). Runs on a daemon thread; the
    pubsub inbox's drop-oldest cap (PyPubsub.MAX_INBOX) bounds memory
    when the driver console is slower than the log storm."""

    def __init__(self, pubsub, channel: str = "logs"):
        self._pubsub = pubsub
        self._sub_id = f"driver-logs-{uuid.uuid4().hex[:8]}"
        self._stop = threading.Event()
        pubsub.subscribe(self._sub_id, channel)
        self._thread = threading.Thread(
            target=self._run, name="ray_tpu-log-printer", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        import json
        try:
            color = sys.stdout.isatty()
        except (ValueError, OSError):
            color = False
        while not self._stop.is_set():
            item = self._pubsub.poll(self._sub_id, timeout=0.25)
            if item is None:
                continue
            try:
                batch = json.loads(item[2])
                out = "\n".join(format_log_batch(batch, color))
                if out:
                    sys.stdout.write(out + "\n")
                    sys.stdout.flush()
            except Exception:  # noqa: BLE001 - printing must not die
                logger.exception("driver log printer failed on a batch")

    def stop(self) -> None:
        self._stop.set()
        try:
            self._pubsub.drop_subscriber(self._sub_id)
        except Exception:  # noqa: BLE001 - pubsub already torn down
            pass
        self._thread.join(timeout=2)
