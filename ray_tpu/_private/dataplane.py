"""Node-to-node object data plane.

The distributed half of the plasma analog (reference:
src/ray/object_manager/object_manager.h:117 node-to-node chunked pulls;
plasma/client.cc cross-process shared memory). Each node daemon owns

* a **NodeObjectTable** — the node's local object storage. Payloads go
  into the native shared-memory arena (src/ray_tpu_native/shm_store.cc)
  when it is available, so *worker processes on the same host attach the
  arena by name and read zero-copy*; a plain heap dict is the fallback.
* an **ObjectServer** — a TCP listener serving chunked object pulls to
  peer daemons (reference: ObjectManagerService gRPC chunked transfer,
  default 5MB chunks, pull_manager.h).

Task arguments whose payload lives on another daemon travel as an
:class:`ObjectMarker` naming the owner's object-server address; the
executing daemon pulls the bytes **directly from the peer** — zero bytes
transit the head. Pulled objects are cached in the local table, so
subsequent tasks on the same node resolve locally (the locality property
plasma gets from node-resident copies).

Transfer accounting (``pulled_bytes`` / ``served_bytes`` per node,
exposed through the daemon stats channel) exists so tests can assert the
head really is out of the data path.
"""

from __future__ import annotations

import contextlib
import hashlib
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, Optional, Tuple

from ray_tpu._private import builtin_metrics
from ray_tpu._private import chaos
from ray_tpu._private import flow as _flow
from ray_tpu._private.channel import sock_send_parts

logger = logging.getLogger(__name__)

_LEN = struct.Struct(">q")  # signed: -1 = not found
CHUNK_SIZE = 4 << 20  # reference: object_manager default chunk ~5MB


def _send_prefixed(sock, header: bytes, *parts) -> None:
    """Small-frame request/record writes: one scatter-gather call (joins
    below the sendmsg threshold) instead of materializing header+body."""
    sock_send_parts(sock, (header, *parts))

#: Chunked parallel pulls (reference: object_manager.proto chunked
#: transfer + pull_manager.h): payloads above the chunk threshold are
#: fetched as concurrent ranged reads over pooled sockets. Defaults
#: mirror ray_config.py (pull_chunk_bytes / pull_parallelism); daemons
#: push their RayConfig values here via :func:`configure_pulls`, and the
#: RAY_TPU_PULL_CHUNK_BYTES / RAY_TPU_PULL_PARALLELISM env vars override
#: either (so worker subprocesses tune without a config handle).
DEFAULT_PULL_CHUNK_BYTES = 4 << 20
DEFAULT_PULL_PARALLELISM = 4
DEFAULT_PULL_STRIPE_MAX_SOURCES = 4
_pull_cfg: Dict[str, int] = {}

#: Peers whose object server predates the ranged-read op (protocol v5):
#: after one fallback round-trip per address, pulls skip the probe.
_ranged_unsupported: set = set()


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw:
        try:
            return int(float(raw))
        except ValueError:
            pass
    return default


def pull_chunk_bytes() -> int:
    """Ranged-read chunk size; <= 0 disables chunked pulls."""
    return _env_int("RAY_TPU_PULL_CHUNK_BYTES",
                    _pull_cfg.get("chunk_bytes", DEFAULT_PULL_CHUNK_BYTES))


def pull_parallelism() -> int:
    """Max concurrent ranged-read sockets per pull."""
    return max(1, _env_int("RAY_TPU_PULL_PARALLELISM",
                           _pull_cfg.get("parallelism",
                                         DEFAULT_PULL_PARALLELISM)))


def pull_stripe_max_sources() -> int:
    """How many distinct holders one chunked pull stripes ranges across
    concurrently. 1 restores the pre-striping behavior (alternate
    holders are failover-only)."""
    return max(1, _env_int("RAY_TPU_PULL_STRIPE_MAX_SOURCES",
                           _pull_cfg.get("stripe_max_sources",
                                         DEFAULT_PULL_STRIPE_MAX_SOURCES)))


def configure_pulls(chunk_bytes: Optional[int] = None,
                    parallelism: Optional[int] = None,
                    stripe_max_sources: Optional[int] = None) -> None:
    """Install config-table values as this process's pull defaults
    (env vars still win; see pull_chunk_bytes/pull_parallelism)."""
    if chunk_bytes is not None:
        _pull_cfg["chunk_bytes"] = int(chunk_bytes)
    if parallelism is not None:
        _pull_cfg["parallelism"] = int(parallelism)
    if stripe_max_sources is not None:
        _pull_cfg["stripe_max_sources"] = int(stripe_max_sources)


class ObjectPullError(ConnectionError):
    """A node-to-node object pull failed (owner unreachable or the object
    is gone). The head treats this as a SYSTEM failure — the task retries
    within its system budget while object reconstruction re-runs the
    producing task (reference: pull retry + object_recovery_manager)."""


class ObjectMarker:
    """Wire marker for a task argument resident in a node object table.

    ``owner_addr is None`` means "local to the target daemon" (the
    plasma-local read). Otherwise the executing daemon pulls from
    ``owner_addr`` (a peer daemon's object server). ``alt_addrs`` are
    additional known holders (replica copies learned by the head's
    location table): a pull that loses ``owner_addr`` mid-flight fails
    over to them chunk-by-chunk instead of erroring into
    reconstruction. ``spill_uri`` is a durable spilled copy any node
    can restore when every holder is gone."""

    __slots__ = ("key", "owner_addr", "size", "alt_addrs", "spill_uri")

    def __init__(self, key: str, owner_addr: Optional[Tuple[str, int]] = None,
                 size: int = 0, alt_addrs=(), spill_uri: Optional[str] = None):
        self.key = key
        self.owner_addr = owner_addr
        self.size = size
        self.alt_addrs = tuple(alt_addrs)
        self.spill_uri = spill_uri


class NodeObjectTable:
    """Local object storage for one node: shm arena preferred (so sibling
    worker processes map payloads zero-copy), heap dict fallback.

    With ``spill_dir`` set (and an arena), the table NEVER loses data to
    memory pressure: arena auto-eviction is disabled, and when a put/pull
    doesn't fit, cold (sealed, unpinned) objects are spilled to disk in
    LRU order and restored transparently on the next read (reference:
    raylet-orchestrated spill/restore, src/ray/raylet/
    local_object_manager.h + object_manager/spilled_object_reader.h).
    Losing an object then requires node death, not a busy shuffle."""

    def __init__(self, capacity: int = 0, arena_name: Optional[str] = None,
                 spill_dir: Optional[str] = None, spill_backend=None):
        self._heap: Dict[str, bytes] = {}
        self._lock = threading.Lock()
        self._arena = None
        self.admission = None  # Optional[PullAdmission]
        self.stats = {"pulled_bytes": 0, "served_bytes": 0,
                      "pulls": 0, "serves": 0,
                      "spilled_bytes": 0, "spilled_objects": 0,
                      "restored_bytes": 0, "restores": 0}
        # Best-effort usage accounting for the resource syncer (with
        # spill enabled the arena never drops entries on its own, so
        # this is exact there; the syncer's view is advisory anyway).
        self._sizes: Dict[str, int] = {}
        #: key -> (disk path, payload size) for spilled objects. Entries
        #: are registered BEFORE the arena copy is deleted, so a reader
        #: always finds the object in at least one of the two places.
        #: Guarded by self._lock (NEVER held across disk I/O — spilled-
        #: object reads must not stall behind a bulk spill batch).
        self._spilled: Dict[str, Tuple[str, int]] = {}
        #: Freed-while-pinned keys (guarded by self._lock): with arena
        #: auto-eviction disabled, a pinned entry survives free(); the
        #: next spill pass must DELETE it, never spill-resurrect it.
        self._doomed: set = set()
        # Owner-side borrow directory (ownership phase 3 — reference:
        # reference_count.h:61 the OWNER tracks its objects' borrowers).
        # key -> live borrow count, registered by peers over borrow
        # channels (ObjectServer '!borrow'); a free() that arrives while
        # borrows are held DEFERS — bytes survive until the last
        # borrower releases, even if the head already dropped its
        # directory entry. Guarded by self._lock.
        self._borrows: Dict[str, int] = {}
        self._deferred_free: set = set()
        # Serializes victim selection across concurrent _make_room
        # callers (one spill batch at a time); dict reads never take it.
        self._spill_lock = threading.Lock()
        self._spill_seq = 0  # per-write spill filename uniquifier
        self._spill_dir: Optional[str] = None
        self._spill_backend = None  # _private.spill.SpillBackend
        #: key -> durable spill URI, announced to the head so recovery
        #: can restore the payload after this daemon dies.
        self._spill_uris: Dict[str, str] = {}
        # Daemon-installed notices (fired outside self._lock): the head
        # learns durable spill URIs through these.
        self.on_spilled = None  # fn(key, uri, size)
        self.on_unspilled = None  # fn(key)
        if capacity > 0:
            try:
                from ray_tpu._private.native_store import NativeObjectStore
                self._arena = NativeObjectStore(capacity=capacity,
                                                name=arena_name)
            except Exception:  # noqa: BLE001 - no compiler → heap fallback
                self._arena = None
        if self._arena is not None and (spill_dir or spill_backend
                                        is not None):
            if spill_backend is None:
                from ray_tpu._private.spill import FileSpillBackend
                spill_backend = FileSpillBackend(spill_dir)
            self._spill_backend = spill_backend
            self._spill_dir = spill_backend.root
            self._arena.set_evict_disabled(True)

    def set_spill_backend(self, backend) -> None:
        """Swap the backend for FUTURE spill writes (the daemon upgrades
        file:// → session:// once registration hands it the session id).
        Already-written records carry absolute paths, so they stay
        readable under the old root."""
        if self._arena is None or backend is None:
            return
        self._spill_backend = backend
        self._spill_dir = backend.root
        self._arena.set_evict_disabled(True)

    # -- disk spill / restore -------------------------------------------

    def _spill_name(self, key: str) -> str:
        # Unique per WRITE, not per key: free() deletes its popped
        # record's path outside the lock, so a deterministic name would
        # let that deferred delete destroy a racing re-put's fresh
        # spill file. Each record carries its own path.
        with self._lock:
            self._spill_seq += 1
            seq = self._spill_seq
        return f"{hashlib.sha1(key.encode()).hexdigest()}-{seq}"

    def _spill_one(self, key: str) -> int:
        """Copy one sealed arena object to disk and drop the arena copy.
        Returns bytes freed (0 if the object vanished or is pinned)."""
        with self._lock:
            if key in self._doomed:
                # free() ran while a reader pinned this entry: reclaim,
                # never spill — a resurrected freed object would leak on
                # disk until daemon shutdown (nobody will ever free it
                # again). Delete under the lock: a racing put() revival
                # (which discards doomed under this lock) can never have
                # its live object destroyed. free() already popped
                # _sizes, so size via a transient pin.
                view = self._arena.get_bytes(key)
                size = 0
                if view is not None:
                    size = len(view)
                    try:
                        view.release()
                    except BufferError:
                        pass
                    self._arena.release(key)
                if self._arena.delete(key):
                    self._doomed.discard(key)
                    return size
                return 0  # still pinned; a later pass retries
        view = self._arena.get_bytes(key)
        if view is None:
            return 0
        size = len(view)
        backend = self._spill_backend
        try:
            # Atomic write-then-rename + fsync live in the backend, as
            # do the spill.write_error chaos site and failure counter.
            uri = backend.write(self._spill_name(key), view)
        except OSError:
            logger.warning("spill of %s failed; keeping in-arena copy",
                           key)
            return 0
        finally:
            try:
                view.release()
            except BufferError:
                pass
            self._arena.release(key)
        return self._register_spill(key, backend.path_for(uri), size,
                                    drop_arena=True, uri=uri)

    def _register_spill(self, key: str, path: str, size: int,
                        drop_arena: bool, uri: Optional[str] = None
                        ) -> int:
        """Commit a written spill file: register it, drop the arena copy
        (when one exists), and honor a free() that raced the disk write
        — our read pin made free's arena delete fail and set _doomed, so
        without the re-check the freed key would resurrect as a spill
        record nobody ever frees. Returns bytes freed from the arena.

        EVERY path re-checks liveness via _sizes (free() pops it): a
        free() that fully completed during the disk write — including
        one whose arena delete SUCCEEDED in the window between
        _spill_one's pin release and this registration, leaving no
        doomed marker — means the file must be discarded, never
        registered.

        A registration through a DURABLE backend announces its URI via
        ``on_spilled`` — the head records it in the object location
        table so node death can restore instead of re-executing."""
        durable = (uri is not None and self._spill_backend is not None
                   and self._spill_backend.durable)
        with self._lock:
            live = key in self._sizes
            if live:
                self._spilled[key] = (path, size)
                if durable:
                    self._spill_uris[key] = uri
        if not live:
            self._spill_backend.delete_path(path)
            return 0
        deleted = self._arena.delete(key) if drop_arena else True
        with self._lock:
            doomed_now = key in self._doomed
            if doomed_now:
                self._spilled.pop(key, None)
                self._spill_uris.pop(key, None)
                if deleted:
                    # Fully reclaimed. A FAILED delete keeps the
                    # tombstone: the arena copy survives (reader pin)
                    # and a later spill pass must still delete, not
                    # spill, it.
                    self._doomed.discard(key)
        if doomed_now:
            self._spill_backend.delete_path(path)
            return size if deleted else 0
        if durable and self.on_spilled is not None:
            try:
                self.on_spilled(key, uri, size)
            except Exception:  # noqa: BLE001 - notice is best-effort
                logger.exception("spill notice for %s failed", key)
        if not deleted:
            # Pinned by a concurrent reader: both copies stay (harmless —
            # the arena copy wins on read until pressure retries us).
            return 0
        self._bump("spilled_bytes", size)
        self._bump("spilled_objects")
        return size

    def _make_room(self, nbytes: int) -> bool:
        """Spill LRU victims until ~nbytes are freed (or nothing left to
        spill). Returns True if any bytes were freed."""
        if self._spill_dir is None:
            return False
        freed_any = False
        with self._spill_lock:
            remaining = max(nbytes, 1)
            while remaining > 0:
                victims = self._arena.lru_victims()
                progress = False
                for key in victims:
                    freed = self._spill_one(key)
                    if freed:
                        progress = True
                        freed_any = True
                        remaining -= freed
                        if remaining <= 0:
                            break
                if not progress:
                    break
        return freed_any

    def _spill_payload(self, key: str, payload: bytes) -> bool:
        """Write a payload that cannot fit the arena straight through
        the spill backend. False when the backend itself fails (caller
        falls back to the heap — degraded, but the object is never
        lost)."""
        backend = self._spill_backend
        try:
            uri = backend.write(self._spill_name(key), payload)
        except OSError:
            logger.warning("direct spill of %s failed", key)
            return False
        self._register_spill(key, backend.path_for(uri), len(payload),
                             drop_arena=False, uri=uri)
        return True

    def _read_spilled(self, key: str) -> Optional[bytes]:
        """Read a spilled payload back and try to promote it into the
        arena (so repeat reads are zero-copy again)."""
        with self._lock:
            rec = self._spilled.get(key)
        if rec is None:
            return None
        path, size = rec
        data = self._spill_backend.read_path(path, size)
        if data is None:
            # Lost a promote race (winner popped the record and deleted
            # the file), freed for real, or an injected restore fault —
            # the CALLER re-checks the arena before concluding the
            # object is gone.
            return None
        self._bump("restored_bytes", size)
        self._bump("restores")
        # OPPORTUNISTIC promotion only: when the working set overflows
        # the arena, forcing room (spilling OTHER live objects to admit
        # this one) degenerates into restore-A-spills-B / restore-B-
        # spills-A disk thrash — a 10GB shuffle spent its wall clock in
        # exactly that loop. A full arena means the read is served from
        # the bytes in hand; the entry stays on disk (scan-resistant,
        # like plasma's no-evict-for-reads policy).
        promoted = self._arena.put_bytes(key, data)
        if promoted:
            # Cleanup must serialize against _spill_one (which runs
            # wholly under _spill_lock): a pressure pass may have
            # ALREADY re-spilled our promoted copy — popping ITS fresh
            # registration and unlinking the file here, after it
            # deleted the arena copy, would lose the object entirely.
            # If the arena no longer holds the key, the spiller's
            # registration is authoritative: keep it.
            with self._spill_lock:
                if self._arena.contains(key):
                    with self._lock:
                        self._spilled.pop(key, None)
                        unspilled = self._spill_uris.pop(key, None)
                        # free() may have raced the promote (it popped
                        # _sizes/_spilled and deleted the file while we
                        # held the payload): with eviction disabled the
                        # promoted copy would otherwise live forever.
                        # The caller still gets the bytes — the read
                        # legitimately raced the free.
                        freed_meanwhile = key not in self._sizes
                    if unspilled is not None and \
                            self.on_unspilled is not None:
                        try:
                            self.on_unspilled(key)
                        except Exception:  # noqa: BLE001 - best-effort
                            logger.exception(
                                "unspill notice for %s failed", key)
                    if freed_meanwhile and not self._arena.delete(key):
                        # Another reader's pin blocked the delete: doom
                        # the zombie so the next spill pass retires it
                        # (else it sits in the no-evict arena forever).
                        # Doom + liveness check in ONE lock block (as
                        # free() does): a put() may have revived the key
                        # since we sampled freed_meanwhile, and a stale
                        # doomed marker would destroy the live payload.
                        with self._lock:
                            if key not in self._sizes:
                                self._doomed.add(key)
                    self._spill_backend.delete_path(path)
                else:
                    # A pressure pass re-spilled our promoted copy and
                    # its registration is authoritative — but if it
                    # wrote a NEW file, the one we read from is now an
                    # orphan nobody will ever delete.
                    with self._lock:
                        rec_now = self._spilled.get(key)
                    if rec_now is not None and rec_now[0] != path:
                        self._spill_backend.delete_path(path)
        return data

    @property
    def arena_name(self) -> Optional[str]:
        return self._arena.name if self._arena is not None else None

    def put(self, key: str, payload: bytes) -> None:
        with self._lock:
            self._sizes[key] = len(payload)
            self._doomed.discard(key)  # re-put revives a freed key
        if self._arena is not None:
            if self._arena.put_bytes(key, payload):
                return
            if self._spill_dir is not None:
                # Arena full: spill cold objects and retry, falling back
                # to writing THIS payload to disk when it simply cannot
                # fit (bigger than the arena / everything else pinned).
                if self._make_room(len(payload)) and \
                        self._arena.put_bytes(key, payload):
                    return
                if self._spill_payload(key, payload):
                    return
                # Spill filesystem failed too (disk full): heap below —
                # the last resort that can never lose the object.
        with self._lock:
            self._heap[key] = bytes(payload)

    def put_parts(self, key: str, parts, size: Optional[int] = None) -> None:
        """Store a payload given as a list of bytes-like parts, laid down
        contiguously in ONE arena allocation (the serialize_oob path:
        pickle header + raw array buffers land with a single copy each,
        never joined into an intermediate full-size bytes). Falls back to
        ``put`` of the joined payload when the arena can't take it."""
        if size is None:
            size = sum(len(p) for p in parts)
        if self._arena is not None:
            with self._lock:
                self._sizes[key] = size
                self._doomed.discard(key)
            dup = type(self._arena).DUPLICATE
            off = self._arena.create(key, size)
            if off is dup:
                return  # already stored (idempotent puts, same as put)
            if off is None and self._spill_dir is not None and \
                    self._make_room(size):
                off = self._arena.create(key, size)
                if off is dup:
                    return
            if off is not None:
                try:
                    wview = self._arena.writable_view(off, size)
                    pos = 0
                    if wview is not None:
                        try:
                            for p in parts:
                                n = len(p)
                                wview[pos:pos + n] = p
                                pos += n
                        finally:
                            with contextlib.suppress(BufferError):
                                wview.release()
                    else:
                        for p in parts:
                            self._arena.write_at(off + pos, bytes(p))
                            pos += len(p)
                except BaseException:
                    self._arena.abort(key)
                    with self._lock:
                        self._sizes.pop(key, None)
                    raise
                self._arena.seal(key)
                return
        self.put(key, b"".join(bytes(p) for p in parts))

    @contextlib.contextmanager
    def pinned(self, key: str):
        """Context manager yielding the payload (a zero-copy shm view when
        arena-resident, else bytes) with a read pin held for the duration,
        or None if absent. The pin keeps eviction/free from recycling the
        region mid-read (plasma semantics: client Get holds a buffer ref);
        the view MUST NOT escape the block."""
        if self._arena is not None:
            # Retry while the object still EXISTS somewhere: under churn
            # it ping-pongs between arena and disk (a promote winner pops
            # the record+file while pressure re-spills it), so a fixed
            # number of passes can miss a live object mid-transition.
            # Terminates: absent from both places = truly gone. Capped
            # defensively; one pass does real I/O, so spinning is
            # bounded by actual transitions.
            for _attempt in range(64):
                view = self._arena.get_bytes(key)  # takes an arena ref
                if view is not None:
                    try:
                        yield view
                    finally:
                        try:
                            view.release()
                        except BufferError:
                            pass  # transient exports; GC drops soon
                        self._arena.release(key)
                        self._reclaim_if_doomed(key)
                    return
                if self._spill_dir is None:
                    break
                data = self._read_spilled(key)
                if data is not None:
                    yield data
                    return
                with self._lock:
                    spilled_present = key in self._spilled
                if not spilled_present and not self._arena.contains(key):
                    break  # gone from both: freed (or never here)
        with self._lock:
            payload = self._heap.get(key)
        yield payload

    def adopt(self, key: str, size: int) -> bool:
        """Take bookkeeping ownership of an arena entry written directly
        by a sibling process (worker-subprocess put): register its size
        so spill liveness sees it, and confirm residency. A pressure
        pass may have SPILLED the pre-adoption entry to disk already —
        that copy is just as adoptable (the table serves it via
        _read_spilled); only a truly absent key fails. The re-check
        closes the race with a spill pass DISCARDING the entry (its
        liveness check fails for keys without _sizes).
        False = already evicted everywhere — the caller must fall back."""
        if self._arena is None:
            return False
        with self._lock:
            spilled = key in self._spilled
        if not spilled and not self._arena.contains(key):
            return False
        with self._lock:
            self._sizes[key] = size
            self._doomed.discard(key)
        if self.contains(key):
            return True
        with self._lock:
            if key in self._spilled:  # landed on disk mid-adoption
                return True
            self._sizes.pop(key, None)
        return False

    def _reclaim_if_doomed(self, key: str) -> None:
        """Freed-while-pinned entries reclaim when a read pin drops —
        without this, a quiet workload (no further _make_room passes)
        would hold the freed bytes in the no-evict arena forever.
        The delete happens UNDER the lock (a leaf microsecond call): a
        racing put() revival discards doomed under the same lock, so we
        can never destroy a just-revived live object."""
        with self._lock:
            if key in self._doomed and self._arena.delete(key):
                self._doomed.discard(key)

    def spill_uri_for(self, key: str) -> Optional[str]:
        """The durable spill URI for a resident key, if one exists."""
        with self._lock:
            return self._spill_uris.get(key)

    def stat(self, key: str) -> int:
        """Payload size if resident (any tier), -1 if not — from the
        bookkeeping records only, never materializing spilled bytes."""
        with self._lock:
            s = self._sizes.get(key)
            if s is not None:
                return s
            h = self._heap.get(key)
            if h is not None:
                return len(h)
            rec = self._spilled.get(key)
            if rec is not None:
                return rec[1]
        return -1

    def contains(self, key: str) -> bool:
        with self._lock:
            if key in self._doomed:
                return False  # freed; only awaiting physical reclaim
            if key in self._spilled:
                return True
            in_heap = key in self._heap
        if in_heap:
            return True
        return self._arena is not None and self._arena.contains(key)

    def servable(self, key: str) -> int:
        """Size if the object can be SERVED right now (sealed in the
        arena, on the heap, or spilled to disk), -1 otherwise. Differs
        from ``stat``: ``put`` records the size before the payload bytes
        land/seal, so a stat-positive key may still be mid-copy — the
        wait op must not wake a puller onto an unsealed entry."""
        with self._lock:
            if key in self._doomed:
                return -1
            h = self._heap.get(key)
            if h is not None:
                return len(h)
            rec = self._spilled.get(key)
            if rec is not None:
                return rec[1]
        if self._arena is not None:
            view = self._arena.get_bytes(key)  # None until sealed
            if view is not None:
                try:
                    return len(view)
                finally:
                    try:
                        view.release()
                    except BufferError:
                        pass
                    self._arena.release(key)
        return -1

    def borrow_add(self, key: str) -> bool:
        """Owner-side borrow registration: a peer context deserialized a
        ref to this object. False when the object is already gone (the
        borrower must fall back to the head's lineage path)."""
        with self._lock:
            if key not in self._sizes and key not in self._heap and \
                    key not in self._spilled:
                return False
            self._borrows[key] = self._borrows.get(key, 0) + 1
            return True

    def borrow_del(self, key: str) -> None:
        """A borrower released (explicitly or by its channel dying).
        The LAST release executes any free() deferred while borrowed."""
        run_free = False
        with self._lock:
            n = self._borrows.get(key, 0) - 1
            if n > 0:
                self._borrows[key] = n
            else:
                self._borrows.pop(key, None)
                run_free = key in self._deferred_free
                self._deferred_free.discard(key)
        if run_free:
            self.free(key)

    def free(self, key: str) -> None:
        with self._lock:
            if self._borrows.get(key, 0) > 0:
                # Owner authority over lifetime: live borrowers keep the
                # bytes; the actual free runs on the last borrow_del.
                self._deferred_free.add(key)
                return
        dead_pin = False
        if self._arena is not None:
            # Read pins are balanced by pinned(); delete fails (-2) only
            # while a concurrent read holds the entry. With eviction
            # disabled (spill mode) nothing would ever reclaim it, so
            # mark it doomed: the next spill pass deletes instead of
            # spilling (a freed object must never be resurrected to
            # disk with no remaining freer).
            dead_pin = not self._arena.delete(key) and \
                self._spill_dir is not None and \
                self._arena.contains(key)
        # ONE lock block: _register_spill's liveness check (_sizes) and
        # record registration must see free's mutations atomically — a
        # pop of _spilled before _sizes in separate blocks let an
        # in-flight spill re-register the freed key between them.
        with self._lock:
            if dead_pin:
                self._doomed.add(key)
            self._sizes.pop(key, None)
            rec = self._spilled.pop(key, None)
            unspilled = self._spill_uris.pop(key, None)
            self._heap.pop(key, None)
        if rec is not None:
            self._spill_backend.delete_path(rec[0])
        if unspilled is not None and self.on_unspilled is not None:
            try:
                self.on_unspilled(key)
            except Exception:  # noqa: BLE001 - notice is best-effort
                logger.exception("unspill notice for %s failed", key)

    def usage(self) -> Dict[str, int]:
        with self._lock:
            return {"objects": len(self._sizes),
                    "bytes": sum(self._sizes.values()),
                    **self.stats}

    def _bump(self, counter: str, n: int = 1) -> None:
        with self._lock:
            self.stats[counter] += n

    def begin_recv(self, key: str, size: int) -> "_RecvLanding":
        """Open an offset-ranged landing for ``size`` incoming bytes:
        an unsealed arena allocation when it fits (chunks recv straight
        into disjoint slices of the shm mapping), a preallocated spill
        file written via ``pwrite`` when it doesn't, a heap buffer with
        no arena. Disjoint ranges may be filled concurrently by multiple
        chunk threads; the single coordinating caller then ``commit``s
        (publish) or ``abort``s (no trace left)."""
        with self._lock:
            # Re-receiving a key freed-while-pinned revives it (same as
            # put): a stale doomed marker would make the next spill pass
            # DELETE the live payload instead of spilling it.
            self._doomed.discard(key)
        if self._arena is not None:
            dup = type(self._arena).DUPLICATE
            off = self._arena.create(key, size)
            if off is None and self._spill_dir is not None and \
                    self._make_room(size):
                off = self._arena.create(key, size)
            if off is dup:
                # Key already stored (racing re-pull): drain the bytes
                # into a scratch landing whose commit is a no-op — the
                # resident payload wins, same as put's idempotence.
                return _RecvLanding(self, key, size,
                                    buf=bytearray(size), discard=True)
            if off is not None:
                wview = self._arena.writable_view(off, size)
                return _RecvLanding(self, key, size, wview=wview, off=off)
            if self._spill_dir is not None:
                # Won't fit even after spilling: land on backend storage
                # directly (chaos spill.write_error covers the open; a
                # failed landing falls back to the heap below).
                try:
                    sl = self._spill_backend.create_landing(
                        self._spill_name(key), size)
                except OSError:
                    logger.warning(
                        "spill landing for %s failed; landing on heap",
                        key)
                else:
                    return _RecvLanding(self, key, size, slanding=sl)
        return _RecvLanding(self, key, size, buf=bytearray(size))

    def recv_into(self, key: str, size: int, sock: socket.socket) -> None:
        """Stream ``size`` bytes from ``sock`` into the table — straight
        into the shm arena when possible (no full-size heap staging)."""
        landing = self.begin_recv(key, size)
        try:
            landing.recv_range(sock, 0, size)
        except BaseException:
            landing.abort()
            raise
        landing.commit()

    def close(self) -> None:
        if self._arena is not None:
            try:
                self._arena.close()
            except Exception:  # noqa: BLE001
                pass
            self._arena = None
        with self._lock:
            spilled = list(self._spilled.values())
            self._spilled.clear()
            self._spill_uris.clear()
        for path, _size in spilled:
            if self._spill_backend is not None:
                self._spill_backend.delete_path(path)
        self._heap.clear()


class _RecvLanding:
    """One in-progress streamed landing (see NodeObjectTable.begin_recv).

    Three backends, chosen by the table:

    * **arena** — unsealed create() allocation; ranges recv_into
      disjoint slices of one writable shm mapping (zero staging copies).
      writable_view's single-writer caveat is about the allocation as a
      whole — disjoint slices from different chunk threads never alias.
    * **disk** — preallocated ``<spill>.tmp`` file; ranges recv into a
      scratch buffer and ``os.pwrite`` at their offset, committed with
      an atomic rename + spill registration.
    * **heap** — preallocated bytearray (no arena available).

    ``commit`` publishes (seal / rename / heap insert) and ``abort``
    leaves no half-written entry behind — a failed pull must never be
    readable."""

    __slots__ = ("_table", "key", "size", "_wview", "_off", "_fd",
                 "_path", "_buf", "_discard", "_sl")

    def __init__(self, table: NodeObjectTable, key: str, size: int, *,
                 wview=None, off: Optional[int] = None,
                 slanding=None,
                 buf: Optional[bytearray] = None, discard: bool = False):
        self._table = table
        self.key = key
        self.size = size
        self._wview = wview
        self._off = off
        self._sl = slanding  # _private.spill.SpillLanding (disk backend)
        self._fd = slanding.fd if slanding is not None else None
        self._path = slanding.path if slanding is not None else None
        self._buf = buf
        self._discard = discard

    def recv_range(self, sock: socket.socket, offset: int,
                   length: int) -> None:
        """Receive exactly ``length`` bytes from ``sock`` into
        [offset, offset+length) of the landing. Thread-safe for
        disjoint ranges."""
        if self._wview is not None:
            view = self._wview[offset:offset + length]
        elif self._buf is not None:
            view = memoryview(self._buf)[offset:offset + length]
        else:
            view = None
        if view is not None:
            received = 0
            while received < length:
                n = sock.recv_into(view[received:],
                                   min(CHUNK_SIZE, length - received))
                if n == 0:
                    raise ConnectionError("peer closed mid-transfer")
                received += n
            return
        # No writable mapping: stage through a scratch buffer, flushing
        # to the arena (write_at) or the spill file (pwrite) per chunk.
        scratch = bytearray(min(CHUNK_SIZE, length))
        sview = memoryview(scratch)
        written = 0
        while written < length:
            want = min(len(scratch), length - written)
            n = sock.recv_into(sview[:want], want)
            if n == 0:
                raise ConnectionError("peer closed mid-transfer")
            if self._fd is not None:
                os.pwrite(self._fd, sview[:n], offset + written)
            else:
                self._table._arena.write_at(self._off + offset + written,
                                            bytes(sview[:n]))
            written += n

    def commit(self) -> None:
        table = self._table
        if self._discard:
            return  # duplicate landing: the resident payload wins
        if self._sl is not None:
            self._sl.commit()  # fsync + atomic rename in the backend
            with table._lock:
                table._sizes[self.key] = self.size
            table._register_spill(self.key, self._sl.path, self.size,
                                  drop_arena=False, uri=self._sl.uri)
            return
        if self._buf is not None:
            with table._lock:
                table._heap[self.key] = bytes(self._buf)
                table._sizes[self.key] = self.size
            return
        if self._wview is not None:
            with contextlib.suppress(BufferError):
                self._wview.release()
            self._wview = None
        table._arena.seal(self.key)
        with table._lock:
            table._sizes[self.key] = self.size

    def abort(self) -> None:
        """Discard without publishing: abort the unsealed arena entry /
        unlink the tmp spill file. Never raises."""
        try:
            if self._sl is not None:
                self._sl.abort()
            elif self._buf is None:
                if self._wview is not None:
                    with contextlib.suppress(BufferError):
                        self._wview.release()
                    self._wview = None
                self._table._arena.abort(self.key)
        except Exception:  # noqa: BLE001 - abort is best-effort cleanup
            pass


#: Pull priority classes (reference: pull_manager.h BundlePriority —
#: task ARGS beat task returns beat plain gets when budget is scarce).
PULL_PRIORITY_TASK_ARGS = 0
PULL_PRIORITY_WORKER_ARGS = 1
PULL_PRIORITY_GET = 2


class PullAdmission:
    """Bounds bytes simultaneously in flight into one node's table
    (reference: pull_manager.h:52 PullManager): a pull learns its size
    from the serving peer's header, then waits here until the budget
    admits it — highest-priority waiter first, FIFO within a class. An
    object larger than the whole budget is admitted alone (head-of-line,
    budget idle) rather than deadlocking."""

    def __init__(self, max_inflight_bytes: int):
        self.capacity = max(1, int(max_inflight_bytes))
        self._inflight = 0
        self._seq = 0
        self._waiting: list = []  # sorted (priority, seq) keys
        self._cond = threading.Condition()
        self.stats = {"admitted": 0, "waited": 0, "peak_inflight": 0}

    def acquire(self, nbytes: int, priority: int = PULL_PRIORITY_GET
                ) -> None:
        with self._cond:
            self._seq += 1
            me = (priority, self._seq)
            import bisect
            bisect.insort(self._waiting, me)
            waited = False
            while True:
                fits = self._inflight + nbytes <= self.capacity or \
                    (self._inflight == 0 and nbytes > self.capacity)
                if fits and self._waiting[0] == me:
                    self._waiting.pop(0)
                    self._inflight += nbytes
                    self.stats["admitted"] += 1
                    if waited:
                        self.stats["waited"] += 1
                    self.stats["peak_inflight"] = max(
                        self.stats["peak_inflight"], self._inflight)
                    self._cond.notify_all()
                    return
                waited = True
                self._cond.wait(timeout=1.0)

    def release(self, nbytes: int) -> None:
        with self._cond:
            self._inflight -= nbytes
            self._cond.notify_all()


class ObjectServer:
    """Serves chunked object pulls from this node's table to peers.

    Protocol: client sends a length-prefixed key; server replies an
    8-byte signed size (-1 = not here), then the raw payload. Special
    key forms: ``?<key>`` (stat: size reply only), ``!borrow`` (switch
    the connection to a borrow channel), and — protocol v6 — the
    ranged-read op ``@<offset>:<length>:<key>`` replying ``length``
    then exactly that payload slice. Ranged reads are deliberately
    encoded as ordinary key strings: a v5 server treats one as an
    unknown key and answers -1 with its framing intact, so a v6 puller
    falls back to the whole-object fetch without desyncing the pooled
    connection.

    The caller binds this to the SAME interface the daemon advertises to
    the head (its head-facing IP) — never unconditionally 0.0.0.0: object
    payloads are served unauthenticated, exactly like the control plane,
    so the exposure policy must match."""

    def __init__(self, table: NodeObjectTable, host: str = "127.0.0.1"):
        self.table = table
        self._listener = socket.create_server((host, 0))
        self.port = self._listener.getsockname()[1]
        self._closed = False
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="ray_tpu-object-server",
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one, args=(sock,),
                             daemon=True).start()

    def _serve_one(self, sock: socket.socket) -> None:
        """Keep-alive request loop: peers pool their connections and
        issue many pulls per socket (one TCP+thread setup amortized
        over a whole shuffle, like the reference's persistent
        object-manager RPC channels). The 30s idle timeout reaps
        abandoned pooled connections."""
        try:
            while True:
                sock.settimeout(30)
                (klen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
                if klen <= 0 or klen > 4096:
                    return  # garbage request; keys are short
                key = _recv_exact(sock, klen).decode()
                if key == "!borrow":
                    # Persistent borrow channel: this connection IS the
                    # borrower's liveness token (ownership phase 3) —
                    # its death releases everything it registered,
                    # exactly like a head client-session's pins.
                    self._serve_borrow_channel(sock)
                    return
                if key.startswith("?"):
                    # Location query answered by the OWNER, not the
                    # head (reference: ownership_based_object_directory
                    # — the directory asks owners). Size from the
                    # records only — never materializes spilled bytes.
                    sock.sendall(_LEN.pack(self.table.stat(key[1:])))
                    continue
                if key.startswith("@"):
                    self._serve_ranged(sock, key)
                    continue
                if key.startswith("~"):
                    self._serve_wait(sock, key)
                    continue
                # The pin spans the whole send: a concurrent free
                # cannot recycle the region under us mid-transfer.
                t0 = time.monotonic()
                with self.table.pinned(key) as payload:
                    if payload is None:
                        sock.sendall(_LEN.pack(-1))
                        continue
                    size = len(payload)
                    # One scatter-gather write: size header + the pinned
                    # arena view go arena->kernel with zero intermediate
                    # copies (sendmsg advances past partial writes with
                    # transient memoryview slices only; nothing exports
                    # the buffer past the context exit).
                    sock_send_parts(
                        sock, (_LEN.pack(size), memoryview(payload)))
                self.table._bump("served_bytes", size)
                self.table._bump("serves")
                self._record_serve(sock, key, size,
                                   time.monotonic() - t0)
        except (OSError, ConnectionError, struct.error):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _serve_ranged(self, sock: socket.socket, key: str) -> None:
        """Ranged-read op (v6): ``@<offset>:<length>:<key>`` replies the
        slice length then payload[offset:offset+length]. A request the
        object can't satisfy (gone, or it changed size since the
        puller's stat) answers -1 — the puller aborts its landing and
        restarts from a fresh stat."""
        try:
            off_s, len_s, real = key[1:].split(":", 2)
            offset, length = int(off_s), int(len_s)
        except ValueError as exc:
            raise ConnectionError(f"malformed ranged request {key!r}"
                                  ) from exc
        t0 = time.monotonic()
        with self.table.pinned(real) as payload:
            if payload is None or offset < 0 or length <= 0 or \
                    offset + length > len(payload):
                sock.sendall(_LEN.pack(-1))
                return
            # Header + the requested slice in one scatter-gather write
            # (memoryview slice: no copy of the pinned region).
            sock_send_parts(
                sock, (_LEN.pack(length),
                       memoryview(payload)[offset:offset + length]))
        self.table._bump("served_bytes", length)
        self.table._bump("serves")
        self._record_serve(sock, real, length, time.monotonic() - t0)

    def _serve_wait(self, sock: socket.socket, key: str) -> None:
        """Blocking stat op: ``~<timeout_ms>:<key>`` parks until the
        object is resident (tree-broadcast children start pulling the
        moment their parent's copy commits, instead of polling), then
        replies its size; -1 at the timeout. Encoded as an ordinary key
        so a pre-wait peer answers -1 with framing intact and the
        caller degrades to client-side retry."""
        try:
            ms_s, real = key[1:].split(":", 1)
            deadline = time.monotonic() + max(0, int(ms_s)) / 1000.0
        except ValueError as exc:
            raise ConnectionError(f"malformed wait request {key!r}"
                                  ) from exc
        from ray_tpu._private.channel import Backoff
        bo = Backoff(0.02, 0.25)
        while True:
            # servable, not stat: put() records the size before the
            # payload seals, and waking a puller mid-copy hands it a
            # "not resident" miss on a GB-scale landing.
            size = self.table.servable(real)
            if size >= 0 or self._closed or \
                    time.monotonic() >= deadline:
                sock.sendall(_LEN.pack(size))
                return
            bo.sleep()

    @staticmethod
    def _record_serve(sock: socket.socket, key: str, size: int,
                      duration_s: float) -> None:
        """One egress ledger entry per served request. The server only
        knows the peer's ephemeral port, so these records aggregate
        into per-node egress totals head-side (never matrix cells)."""
        try:
            peer = sock.getpeername()
        except OSError:
            peer = None
        try:
            _flow.global_flow_recorder().record(
                key=key, nbytes=size, duration_s=duration_s,
                direction="out", peer=peer)
        except Exception:  # noqa: BLE001 - accounting must not kill serves
            pass

    def _serve_borrow_channel(self, sock: socket.socket) -> None:
        """Channel records: '+<key>' register, '-<key>' release — both
        ackless one-way notices (the borrower never blocks a hot
        deserialization path on the owner; a failed registration only
        costs it the fast path, the head pin still guards lifetime).
        Connection death releases every borrow the channel holds."""
        held: Dict[str, int] = {}
        try:
            sock.settimeout(None)  # idle channels are normal
            while True:
                (rlen,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
                if rlen <= 0 or rlen > 4096:
                    return
                rec = _recv_exact(sock, rlen).decode()
                op, key = rec[0], rec[1:]
                if op == "+":
                    if self.table.borrow_add(key):
                        held[key] = held.get(key, 0) + 1
                elif op == "-":
                    n = held.get(key, 0)
                    if n > 0:
                        held[key] = n - 1
                        if held[key] == 0:
                            del held[key]
                        self.table.borrow_del(key)
                else:
                    return
        except (OSError, ConnectionError, struct.error):
            pass
        finally:
            for key, n in held.items():
                for _ in range(n):
                    self.table.borrow_del(key)
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass


class BorrowChannel:
    """Client-side half of an owner borrow channel: one persistent
    connection to an owner daemon's object server, registering this
    PROCESS's borrows of that owner's objects. The connection doubles
    as the liveness lease — if this process dies, the owner releases
    everything the channel held. Used ONLY by the BorrowChannels
    flusher thread (and tests) — never from hot paths."""

    def __init__(self, addr: Tuple[str, int], timeout: float = 5.0):
        self.addr = tuple(addr)
        self._sock = socket.create_connection(self.addr, timeout=timeout)
        self._sock.settimeout(timeout)
        kb = b"!borrow"
        _send_prefixed(self._sock, _LEN.pack(len(kb)), kb)
        self._lock = threading.Lock()
        #: keys this CHANNEL GENERATION successfully registered (count).
        #: A '-' may only ride the generation its '+' rode: after a
        #: channel death the owner already released everything it held,
        #: and sending the stale delete on a successor channel would
        #: decrement a DIFFERENT borrower's live registration.
        self.sent_counts: Dict[str, int] = {}

    def add(self, key: str) -> None:
        rec = ("+" + key).encode()
        with self._lock:
            _send_prefixed(self._sock, _LEN.pack(len(rec)), rec)
            self.sent_counts[key] = self.sent_counts.get(key, 0) + 1

    def delete(self, key: str) -> bool:
        """Send the release iff this generation holds the borrow."""
        with self._lock:
            n = self.sent_counts.get(key, 0)
            if n <= 0:
                return False  # registered on a dead predecessor: moot
            rec = ("-" + key).encode()
            _send_prefixed(self._sock, _LEN.pack(len(rec)), rec)
            if n == 1:
                del self.sent_counts[key]
            else:
                self.sent_counts[key] = n - 1
        return True

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class BorrowChannels:
    """Process-wide owner-ward borrow notifier (ownership phase 3).

    ``add``/``delete`` only ENQUEUE — they are called from
    ObjectRef.__init__ (mid-deserialization on hot paths) and
    ObjectRef.__del__ (any thread, any allocation point, possibly
    inside cyclic GC), so they must never touch a lock a socket write
    can hold, never dial, never block. One flusher thread owns every
    channel: it dials owners (connect timeouts stall only itself),
    replays records in order, and drops deletes whose registration
    died with a previous channel generation."""

    def __init__(self):
        from collections import deque
        self._q: Any = deque()
        self._event = threading.Event()
        self._channels: Dict[Tuple[str, int], BorrowChannel] = {}
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()
        self._closed = False

    def add(self, addr: Tuple[str, int], key: str) -> None:
        self._notify(("+", tuple(addr), key))

    def delete(self, addr: Tuple[str, int], key: str) -> None:
        self._notify(("-", tuple(addr), key))

    def _notify(self, rec) -> None:
        self._q.append(rec)
        self._event.set()
        if self._thread is None:
            with self._thread_lock:
                if self._thread is None and not self._closed:
                    self._thread = threading.Thread(
                        target=self._flush_loop,
                        name="ray_tpu-borrow-notices", daemon=True)
                    self._thread.start()

    def _flush_loop(self) -> None:
        while not self._closed:
            self._event.wait()
            self._event.clear()
            while True:
                try:
                    op, addr, key = self._q.popleft()
                except IndexError:
                    break
                try:
                    ch = self._channels.get(addr)
                    if op == "+":
                        if ch is None:
                            ch = BorrowChannel(addr)
                            self._channels[addr] = ch
                        ch.add(key)
                    elif ch is not None:
                        ch.delete(key)
                except (OSError, ConnectionError, struct.error):
                    # Owner unreachable / channel died: the owner has
                    # (or will have) released this generation's borrows;
                    # lifetime stays guarded by the head pin.
                    ch = self._channels.pop(addr, None)
                    if ch is not None:
                        ch.close()

    def close(self) -> None:
        self._closed = True
        self._event.set()
        for ch in list(self._channels.values()):
            ch.close()
        self._channels.clear()


#: The process's borrow channels (lazily populated; worker subprocesses
#: and daemon contexts share one instance per process).
GLOBAL_BORROWS = BorrowChannels()


def _pooled_rpc(addr: Tuple[str, int], timeout: float, op):
    """Run ``op(sock)`` over a pooled peer socket with the shared
    transient-error classification (channel.is_transient): one free
    retry on a fresh connection when a REUSED pooled socket turns out
    stale (peer closed it since release), chaos injection at the
    ``pull.send`` site, socket hygiene on failure. ``op`` releases the
    socket back to the pool itself on success — only it knows whether
    the protocol exchange completed cleanly."""
    from ray_tpu._private.channel import is_transient
    addr = tuple(addr)
    stale_retry = True
    while True:
        sock = None
        reused = False
        try:
            sock, reused = GLOBAL_PEER_CONNS.acquire(addr, timeout)
            if chaos.ACTIVE:
                chaos.maybe_inject("pull.send", sock)
            return op(sock)
        except ObjectPullError:
            raise  # protocol-level miss, not a transport failure
        except BaseException as exc:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            if is_transient(exc) and reused and stale_retry:
                stale_retry = False
                builtin_metrics.channel_send_retries().inc()
                continue  # stale pooled socket: one retry on fresh TCP
            raise


def stat_remote(addr: Tuple[str, int], key: str,
                timeout: float = 10.0) -> int:
    """Owner-ward location query: payload size if resident, -1 if not.
    Never touches the head (phase-3 'directory asks the owner')."""
    addr = tuple(addr)

    def op(sock):
        kb = ("?" + key).encode()
        _send_prefixed(sock, _LEN.pack(len(kb)), kb)
        (size,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
        GLOBAL_PEER_CONNS.release(addr, sock)
        return size

    return _pooled_rpc(addr, timeout, op)


def wait_remote(addr: Tuple[str, int], key: str,
                timeout: float = 30.0) -> int:
    """Block until ``key`` is resident on the peer (the tree-broadcast
    wait: a child's pull parks on its parent's object server until the
    parent's own copy lands). Returns the size, or -1 when the timeout
    expires with the object still absent. Server-side waits go in short
    rounds so pooled-socket timeouts stay tight and a peer that predates
    the wait op (instant -1) degrades to client-side polling."""
    addr = tuple(addr)
    deadline = time.monotonic() + max(0.0, timeout)
    round_s = 5.0
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return stat_remote(addr, key, timeout=round_s)
        wait_ms = int(min(remaining, round_s) * 1000)

        def op(sock, wait_ms=wait_ms):
            kb = f"~{wait_ms}:{key}".encode()
            _send_prefixed(sock, _LEN.pack(len(kb)), kb)
            (size,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
            GLOBAL_PEER_CONNS.release(addr, sock)
            return size

        size = _pooled_rpc(addr, round_s + 10.0, op)
        if size >= 0:
            return size
        # A pre-wait peer answers instantly: don't spin a hot loop.
        time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))


def fetch_remote_bytes(addr: Tuple[str, int], key: str,
                       timeout: float = 30.0) -> bytearray:
    """Pull one object's payload straight into memory (contexts without
    a local NodeObjectTable — e.g. worker subprocesses resolving a
    borrowed ref). Returns a bytes-like buffer (a bytearray: the body
    recv_into's one preallocation, skipping the bytes() copy a borrowed
    multi-MB payload used to pay). Raises ObjectPullError when
    absent/unreachable."""
    addr = tuple(addr)
    t0 = time.monotonic()

    def op(sock):
        kb = key.encode()
        _send_prefixed(sock, _LEN.pack(len(kb)), kb)
        (size,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
        if size < 0:
            GLOBAL_PEER_CONNS.release(addr, sock)
            raise ObjectPullError(
                f"object {key} is not resident on {addr}")
        data = _recv_exact_into(sock, bytearray(size))
        GLOBAL_PEER_CONNS.release(addr, sock)
        try:
            _flow.global_flow_recorder().record(
                key=key, nbytes=size,
                duration_s=time.monotonic() - t0,
                direction="in", peer=addr)
        except Exception:  # noqa: BLE001 - accounting must not fail a pull
            pass
        return data

    try:
        return _pooled_rpc(addr, timeout, op)
    except ObjectPullError:
        raise
    except (OSError, ConnectionError, struct.error) as exc:
        raise ObjectPullError(
            f"direct fetch of {key} from {addr} failed: "
            f"{exc}") from exc


def _recv_exact_into(sock: socket.socket, buf: bytearray) -> bytearray:
    """Fill ``buf`` from the socket via recv_into — no per-chunk bytes
    objects, no growth copies."""
    view = memoryview(buf)
    n = len(buf)
    read = 0
    while read < n:
        m = sock.recv_into(view[read:], n - read)
        if m == 0:
            raise ConnectionError("connection closed")
        read += m
    return buf


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    return bytes(_recv_exact_into(sock, bytearray(n)))


class _PeerConns:
    """Pooled keep-alive connections to peer object servers. One pull
    used to pay a fresh TCP handshake + server thread spawn; pooling
    amortizes both across a shuffle's thousands of pulls (reference:
    object_manager keeps persistent RPC channels per peer)."""

    MAX_IDLE_PER_ADDR = 8

    def __init__(self):
        self._idle: Dict[Tuple[str, int], list] = {}
        self._lock = threading.Lock()

    def acquire(self, addr: Tuple[str, int],
                timeout: float) -> Tuple[socket.socket, bool]:
        """Returns (socket, reused). A reused socket may be stale (the
        server reaped it idle) — the caller retries on a fresh one."""
        addr = tuple(addr)
        with self._lock:
            lst = self._idle.get(addr)
            if lst:
                sock = lst.pop()
                sock.settimeout(timeout)
                return sock, True
        sock = socket.create_connection(addr, timeout=timeout)
        sock.settimeout(timeout)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        return sock, False

    def release(self, addr: Tuple[str, int], sock: socket.socket) -> None:
        addr = tuple(addr)
        with self._lock:
            lst = self._idle.setdefault(addr, [])
            if len(lst) < self.MAX_IDLE_PER_ADDR:
                lst.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def close(self) -> None:
        with self._lock:
            socks = [s for lst in self._idle.values() for s in lst]
            self._idle.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass


GLOBAL_PEER_CONNS = _PeerConns()


def _fetch_chunk(addr: Tuple[str, int], key: str, landing: _RecvLanding,
                 offset: int, length: int, timeout: float) -> bool:
    """One ranged read straight into the landing's [offset, offset+len)
    slice, over a pooled socket. Returns False when the server answered
    -1 — a v5 peer (ranged keys are unknown keys to it) or an object
    that vanished/changed size since the stat."""
    def op(sock):
        kb = f"@{offset}:{length}:{key}".encode()
        _send_prefixed(sock, _LEN.pack(len(kb)), kb)
        (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
        if n < 0:
            GLOBAL_PEER_CONNS.release(addr, sock)
            return False
        if n != length:
            raise ConnectionError(
                f"ranged read of {key} returned {n}, wanted {length}")
        landing.recv_range(sock, offset, length)
        GLOBAL_PEER_CONNS.release(addr, sock)
        return True

    return _pooled_rpc(addr, timeout, op)


def _pull_chunked(addrs, key: str, table: NodeObjectTable,
                  size: int, timeout: float, admission, priority: int,
                  stats: Optional[dict] = None) -> bool:
    """Chunked parallel pull, STRIPED across holders: split [0, size)
    into pull_chunk_bytes() ranges and fetch them concurrently over up
    to pull_parallelism() pooled sockets, each chunk landing straight in
    its slice of the shm arena (or spill file / heap buffer). Returns
    False when the peer lacks the ranged op (v5) — the caller falls back
    to the whole-object fetch. Admission covers the WHOLE object for its
    entire flight, same as the monolithic path, so parallel chunks can't
    oversubscribe the inflight-bytes budget.

    ``addrs`` is the candidate holder list (primary first). Every live
    holder — up to pull_stripe_max_sources() — serves ranges
    CONCURRENTLY: workers are spread round-robin over the stripe set
    (the per-holder inflight cap: each worker keeps at most one ranged
    read outstanding) but all pop from ONE shared range queue, so a
    slow holder's workers simply claim fewer ranges while fast holders'
    workers drain the tail (work-stealing without a rebalancer). A
    holder that dies MID-PULL doesn't fail the pull: it joins a
    monotonic dead set — never retried within this pull, the old shared
    cursor's guarantee generalized to many sources — and its workers
    re-prefer the next live holder; already-landed ranges are kept,
    nothing restarts (reference: pull_manager retries against other
    location-table holders)."""
    addrs = [tuple(a) for a in addrs]
    chunk = pull_chunk_bytes()
    ranges = [(off, min(chunk, size - off)) for off in range(0, size, chunk)]
    if admission is not None:
        admission.acquire(size, priority)
    _flow.global_flow_recorder().begin(size)
    landing = None
    ok = False
    dead: set = set()
    served: Dict[Tuple[str, int], int] = {}
    book_lock = threading.Lock()

    def live_from(start_i: int):
        """First live holder at/after ``start_i`` (wrapping), else
        None — workers stay pinned to their stripe slot until it dies."""
        with book_lock:
            for j in range(len(addrs)):
                h = addrs[(start_i + j) % len(addrs)]
                if h not in dead:
                    return h
        return None

    def fetch_with_failover(off: int, ln: int, prefer_i: int) -> None:
        fail: Optional[BaseException] = None
        while True:
            holder = live_from(prefer_i)
            if holder is None:
                raise ObjectPullError(
                    f"all {len(addrs)} holder(s) failed pulling range "
                    f"{off} of {key}: {fail}") from fail
            try:
                if _fetch_chunk(holder, key, landing, off, ln, timeout):
                    with book_lock:
                        served[holder] = served.get(holder, 0) + ln
                    return
                fail = ObjectPullError(
                    f"peer {holder} dropped range {off} of {key} "
                    "mid-pull")
            except (OSError, ConnectionError, struct.error) as exc:
                fail = exc
            with book_lock:
                dead.add(holder)
            logger.info("pull of %s range %d failing over past dead "
                        "holder %s", key, off, holder)

    try:
        landing = table.begin_recv(key, size)
        # Probe with the first chunk on this thread: a -1 here means a
        # v5 peer (or a vanished object) and nothing has been spawned —
        # but a DEAD primary fails over to the next holder right away.
        probe_i = 0
        while True:
            holder = addrs[probe_i]
            try:
                if not _fetch_chunk(holder, key, landing,
                                    ranges[0][0], ranges[0][1], timeout):
                    return False
                with book_lock:
                    served[holder] = served.get(holder, 0) + ranges[0][1]
                break
            except (OSError, ConnectionError, struct.error):
                with book_lock:
                    dead.add(holder)
                probe_i += 1
                if probe_i >= len(addrs):
                    raise
        rest = ranges[1:]
        if rest:
            from collections import deque
            queue = deque(rest)
            failed = threading.Event()
            errors: list = []
            # The stripe set: the first max_sources candidates. Dead
            # ones are skipped by live_from at fetch time, so a stripe
            # slot over a corpse degrades to the next live holder
            # instead of shrinking the worker pool.
            nsources = min(pull_stripe_max_sources(), len(addrs))

            def fetch_worker(slot: int) -> None:
                prefer_i = slot % nsources
                while not failed.is_set():
                    try:
                        off, ln = queue.popleft()
                    except IndexError:
                        return
                    try:
                        fetch_with_failover(off, ln, prefer_i)
                    except BaseException as exc:  # noqa: BLE001
                        errors.append(exc)
                        failed.set()
                        return

            nworkers = min(pull_parallelism(), len(rest))
            if stats is not None:
                stats["parallelism"] = max(1, nworkers)
            if nworkers <= 1:
                fetch_worker(0)
            else:
                threads = [threading.Thread(
                    target=fetch_worker, args=(i,), daemon=True,
                    name=f"ray_tpu-pull-chunk-{i}")
                    for i in range(nworkers)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            if errors:
                raise errors[0]
        landing.commit()
        ok = True
        table._bump("pulled_bytes", size)
        table._bump("pulls")
        if stats is not None:
            stats["bytes"] = size
            stats["chunks"] = len(ranges)
            stats["failovers"] = stats.get("failovers", 0) + len(dead)
            stats["sources_used"] = max(
                1, sum(1 for n in served.values() if n > 0))
            stats["striped"] = {f"{a[0]}:{a[1]}": n
                                for a, n in served.items() if n > 0}
        return True
    finally:
        if not ok and landing is not None:
            landing.abort()
        _flow.global_flow_recorder().end(size)
        if admission is not None:
            admission.release(size)


def pull_object(addr: Tuple[str, int], key: str, table: NodeObjectTable,
                timeout: float = 30.0, retries: int = 2,
                priority: int = PULL_PRIORITY_GET,
                size_hint: int = 0, fallback_addrs=(),
                tier: str = "replica") -> None:
    """Pull one object from a peer's object server into the local table
    (read it back with ``table.pinned``). Connections are pooled and
    kept alive; a stale pooled socket retries on a fresh one without
    consuming a retry budget. Raises ObjectPullError when every holder
    is unreachable or lacks the object. In-flight bytes are bounded by
    the table's PullAdmission (if set): the size is learned first (stat
    or size header), admission is acquired for the body (args-first
    priority), released when the body lands.

    ``size_hint`` (callers pass the ObjectMarker size) routes payloads
    above pull_chunk_bytes() through the chunked parallel path — one
    authoritative stat round-trip, then concurrent ranged reads. Pulls
    without a hint (or small ones) keep the single-socket flow with no
    extra round-trip. A v5 peer (no ranged op) degrades to the
    whole-object fetch once, then is remembered.

    ``fallback_addrs`` are additional known holders (ObjectMarker
    ``alt_addrs``, fed by the head's location table). Inside the
    chunked path they are STRIPED: up to pull_stripe_max_sources()
    holders serve disjoint ranges concurrently (the aggregate pull
    rides every replica's NIC, not just the primary's), and a failed
    or mid-flight-dead holder's remaining chunks simply resume from
    the next live one instead of erroring into lineage reconstruction
    (reference: pull_manager retrying across object-directory
    locations; PushManager's multi-source chunk scheduling).

    ``tier`` labels this pull's flow-ledger record ("replica" for
    ordinary marker pulls, "push" when a broadcast tree is forwarding
    through this node)."""
    candidates = [tuple(addr)]
    for alt in fallback_addrs or ():
        alt = tuple(alt)
        if alt not in candidates:
            candidates.append(alt)
    # Traced only under an active sampled span (a traced task resolving
    # its args); untraced pulls pay one thread-local read.
    from ray_tpu.util import tracing
    # One typed flow record per pull — the ledger the head aggregates
    # into the per-link matrix. Inner paths fill `stats`; the record
    # (and the span's transfer attributes) are stamped here, once,
    # whether the pull landed or exhausted every holder.
    stats = {"bytes": 0, "chunks": 1, "parallelism": 1, "failovers": 0}
    t0 = time.monotonic()

    def _finish(span, peer, outcome: str) -> None:
        if span is not None:
            span.attributes["bytes"] = stats["bytes"]
            span.attributes["chunks"] = stats["chunks"]
            span.attributes["sources_used"] = stats.get(
                "sources_used", stats["failovers"] + 1)
            span.attributes["failovers"] = stats["failovers"]
        try:
            _flow.global_flow_recorder().record(
                key=key, nbytes=stats["bytes"],
                duration_s=time.monotonic() - t0, direction="in",
                peer=peer, chunks=stats["chunks"],
                parallelism=stats["parallelism"],
                failovers=stats["failovers"], tier=tier,
                outcome=outcome)
        except Exception:  # noqa: BLE001 - accounting must not fail a pull
            pass

    with tracing.child_span("data::pull",
                            {"stage": "pull", "key": key,
                             "size_hint": size_hint}) as span:
        last: Optional[BaseException] = None
        for i, cand in enumerate(candidates):
            try:
                _pull_object_once(cand, key, table, timeout, retries,
                                  priority, size_hint,
                                  others=candidates[i + 1:], stats=stats)
                stats["failovers"] += i
                _finish(span, cand, "ok")
                return
            except (ObjectPullError, OSError, ConnectionError,
                    struct.error) as exc:
                last = exc
                if i + 1 < len(candidates):
                    logger.info("pull of %s from %s failed (%s); failing "
                                "over to %s", key, cand, exc,
                                candidates[i + 1])
        stats["failovers"] += len(candidates) - 1
        _finish(span, candidates[0], "error")
        if isinstance(last, ObjectPullError):
            raise last
        raise ObjectPullError(
            f"pull of {key} failed on all {len(candidates)} holder(s): "
            f"{last}") from last


def _pull_object_once(addr: Tuple[str, int], key: str,
                      table: NodeObjectTable, timeout: float,
                      retries: int, priority: int, size_hint: int,
                      others=(), stats: Optional[dict] = None) -> None:
    """One holder's pull attempt (retry/backoff loop against a single
    primary; ``others`` ride along into the chunked path for mid-pull
    chunk failover)."""
    from ray_tpu._private.channel import Backoff
    last: Optional[BaseException] = None
    admission = getattr(table, "admission", None)
    addr = tuple(addr)
    attempts = 0
    bo = Backoff(0.2, 2.0)
    while attempts <= retries:
        sock = reused = None
        try:
            chunk = pull_chunk_bytes()
            if chunk > 0 and size_hint > chunk and \
                    addr not in _ranged_unsupported:
                size = stat_remote(addr, key, timeout)
                if size < 0:
                    raise ObjectPullError(
                        f"object {key} is not resident on {addr} "
                        "(freed or evicted before the pull)")
                fell_back = False
                if size > chunk:
                    if _pull_chunked([addr, *others], key, table, size,
                                     timeout, admission, priority,
                                     stats=stats):
                        return
                    fell_back = True
                # Whole-object path below; a success after a ranged
                # refusal means the peer is v5 — skip future probes.
                sock, reused = GLOBAL_PEER_CONNS.acquire(addr, timeout)
                if chaos.ACTIVE:
                    chaos.maybe_inject("pull.send", sock)
                _pull_whole(addr, key, table, sock, admission, priority,
                            stats=stats)
                if fell_back:
                    _ranged_unsupported.add(addr)
                return
            sock, reused = GLOBAL_PEER_CONNS.acquire(addr, timeout)
            if chaos.ACTIVE:
                chaos.maybe_inject("pull.send", sock)
            _pull_whole(addr, key, table, sock, admission, priority,
                        stats=stats)
            return
        except ObjectPullError:
            raise
        except (OSError, ConnectionError, struct.error) as exc:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            last = exc
            builtin_metrics.channel_send_retries().inc()
            if reused:
                continue  # stale pooled socket: free retry on fresh TCP
            attempts += 1
            bo.sleep()  # jittered: concurrent pullers spread out
    raise ObjectPullError(
        f"pull of {key} from {addr} failed after {retries + 1} "
        f"attempts: {last}")


def _pull_whole(addr: Tuple[str, int], key: str, table: NodeObjectTable,
                sock: socket.socket, admission, priority: int,
                stats: Optional[dict] = None) -> None:
    """The monolithic single-socket pull: size header, then the body
    streamed into the table. The caller owns socket acquisition and
    error handling (its stale-socket retry convention)."""
    kb = key.encode()
    _send_prefixed(sock, _LEN.pack(len(kb)), kb)
    (size,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if size < 0:
        GLOBAL_PEER_CONNS.release(addr, sock)
        raise ObjectPullError(
            f"object {key} is not resident on {addr} "
            "(freed or evicted before the pull)")
    if admission is not None:
        admission.acquire(size, priority)
    _flow.global_flow_recorder().begin(size)
    try:
        table.recv_into(key, size, sock)
    finally:
        _flow.global_flow_recorder().end(size)
        if admission is not None:
            admission.release(size)
    table._bump("pulled_bytes", size)
    table._bump("pulls")
    if stats is not None:
        stats["bytes"] = size
    GLOBAL_PEER_CONNS.release(addr, sock)
