"""Python binding for the native cluster resource scheduler.

ctypes wrapper over src/ray_tpu_native/sched.cc — the native analog of the
reference's C++ scheduling stack (fixed-point resource vectors,
hybrid/spread policies, placement-group bundle placement; reference:
src/ray/raylet/scheduling/cluster_resource_scheduler.h:44,
scheduling/policy/hybrid_scheduling_policy.h,
scheduling/policy/bundle_scheduling_policy.h).

``NativeClusterResourceScheduler`` is drop-in compatible with the Python
``ClusterResourceScheduler`` (cluster_scheduler.py): the runtime picks the
native engine when the library builds (RAY_TPU_NATIVE_SCHED=0 disables),
and every scheduling decision — node selection, admission accounting, PG
bundle ledger — happens in C++.
"""

from __future__ import annotations

import ctypes
import functools
import os
import threading
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.ids import NodeID, PlacementGroupID
from ray_tpu.exceptions import PlacementGroupError

_lib = None
_lib_lock = threading.Lock()

_PG_STRATEGIES = {"PACK": 0, "SPREAD": 1, "STRICT_PACK": 2,
                  "STRICT_SPREAD": 3}


def _build_library() -> Optional[str]:
    from ray_tpu._private.native_build import build_library
    return build_library("sched")


def _load() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        path = _build_library()
        if path is None:
            return None
        # PyDLL: every call (feasibility probe, acquire, release) is a
        # microsecond map walk on the dispatch hot path; releasing the
        # GIL around it costs a handoff per call under thread churn.
        # Nothing in sched.cc blocks (pure fixed-point arithmetic).
        lib = ctypes.PyDLL(path)
        P, I, L, D, C = (ctypes.c_void_p, ctypes.c_int, ctypes.c_int64,
                        ctypes.c_double, ctypes.c_char_p)
        lib.rsched_create.restype = P
        lib.rsched_destroy.argtypes = [P]
        lib.rsched_add_node.restype = L
        lib.rsched_add_node.argtypes = [P, C]
        lib.rsched_remove_node.restype = I
        lib.rsched_remove_node.argtypes = [P, L]
        lib.rsched_node_alive.restype = I
        lib.rsched_node_alive.argtypes = [P, L]
        lib.rsched_num_nodes.restype = L
        lib.rsched_num_nodes.argtypes = [P]
        lib.rsched_node_resources.restype = L
        lib.rsched_node_resources.argtypes = [P, L, I, C, L]
        lib.rsched_utilization.restype = D
        lib.rsched_utilization.argtypes = [P, L]
        lib.rsched_fits.restype = I
        lib.rsched_fits.argtypes = [P, L, I, C]
        lib.rsched_try_acquire_on.restype = I
        lib.rsched_try_acquire_on.argtypes = [P, L, C]
        lib.rsched_release_on.argtypes = [P, L, C]
        lib.rsched_force_acquire_on.argtypes = [P, L, C]
        lib.rsched_pick_and_acquire.restype = L
        lib.rsched_pick_and_acquire.argtypes = [P, C, I]
        lib.rsched_pg_create.restype = L
        lib.rsched_pg_create.argtypes = [P, C, I]
        lib.rsched_pg_remove.restype = I
        lib.rsched_pg_remove.argtypes = [P, L]
        lib.rsched_pg_exists.restype = I
        lib.rsched_pg_exists.argtypes = [P, L]
        lib.rsched_pg_num_bundles.restype = I
        lib.rsched_pg_num_bundles.argtypes = [P, L]
        lib.rsched_pg_bundle_node.restype = L
        lib.rsched_pg_bundle_node.argtypes = [P, L, I]
        lib.rsched_pg_bundle_resources.restype = L
        lib.rsched_pg_bundle_resources.argtypes = [P, L, I, I, C, L]
        lib.rsched_pg_try_acquire.restype = I
        lib.rsched_pg_try_acquire.argtypes = [P, L, I, C]
        lib.rsched_pg_release.argtypes = [P, L, I, C]
        lib.rsched_pg_force_acquire.argtypes = [P, L, I, C]
        lib.rsched_pg_reschedule_lost.restype = L
        lib.rsched_pg_reschedule_lost.argtypes = [
            P, ctypes.POINTER(L), L]
        _lib = lib
        return _lib


def native_sched_available() -> bool:
    if os.environ.get("RAY_TPU_NATIVE_SCHED", "1") == "0":
        return False
    return _load() is not None


@functools.lru_cache(maxsize=4096)
def _encode_items(items: tuple) -> bytes:
    return ";".join(f"{k}={float(v):.10g}" for k, v in items).encode()


def _encode(resources: Dict[str, float]) -> bytes:
    # Memoized on the items tuple: task resource dicts repeat endlessly
    # (every same-class task encodes the identical map 3x — feasible/
    # acquire/release — on the submit hot path). LRU, not clear-all:
    # >4096 distinct shapes must evict cold entries, never dump the
    # hot set mid-burst.
    return _encode_items(tuple(resources.items()))


def _read_encoded(fn, *args) -> Dict[str, float]:
    """Call a native getter that writes an encoded resource map into a
    caller-provided buffer (returning the needed length), growing the buffer
    until it fits. Returns {} on a negative (error) length."""
    cap = 4096
    while True:
        buf = ctypes.create_string_buffer(cap)
        n = fn(*args, buf, cap)
        if n < 0:
            return {}
        if n < cap:
            return _decode(buf.value)
        cap = n + 1


def _decode(raw: bytes) -> Dict[str, float]:
    out: Dict[str, float] = {}
    if not raw:
        return out
    for part in raw.decode().split(";"):
        k, _, v = part.partition("=")
        out[k] = float(v)
    return out


class _LocalView:
    """NodeState.local-compatible view (total/available) over the native
    node; consumers (autoscaler, state API) read these as dicts."""

    __slots__ = ("_sched", "_handle")

    def __init__(self, sched: "NativeClusterResourceScheduler",
                 handle: int):
        self._sched = sched
        self._handle = handle

    def _read(self, which: int) -> Dict[str, float]:
        return _read_encoded(self._sched._lib.rsched_node_resources,
                             self._sched._h, self._handle, which)

    @property
    def total(self) -> Dict[str, float]:
        return self._read(0)

    @property
    def available(self) -> Dict[str, float]:
        return self._read(1)


class NodeStateView:
    """NodeState-compatible handle onto a native node."""

    def __init__(self, sched: "NativeClusterResourceScheduler",
                 node_id: NodeID, handle: int, resources: Dict[str, float],
                 is_head: bool, labels: Optional[dict]):
        self.node_id = node_id
        self.resources = dict(resources)
        self.is_head = is_head
        self.labels = dict(labels or {})
        self.free_tpu_ids: List[int] = list(
            range(int(resources.get("TPU", 0))))
        self._sched = sched
        self._handle = handle
        self.local = _LocalView(sched, handle)

    @property
    def alive(self) -> bool:
        return bool(self._sched._lib.rsched_node_alive(
            self._sched._h, self._handle))

    def utilization(self) -> float:
        return float(self._sched._lib.rsched_utilization(
            self._sched._h, self._handle))


class NativeClusterResourceScheduler:
    """Drop-in ClusterResourceScheduler backed by the C++ engine."""

    def __init__(self):
        lib = _load()
        if lib is None:
            raise RuntimeError("native scheduler library unavailable")
        self._lib = lib
        self._h = lib.rsched_create()
        self._lock = threading.RLock()
        self._nodes: Dict[NodeID, NodeStateView] = {}
        self._order: List[NodeID] = []
        self._handles: Dict[int, NodeID] = {}
        self._pgs: Dict[PlacementGroupID, int] = {}  # pg id -> native handle
        self._pg_strategies: Dict[PlacementGroupID, str] = {}

    def __del__(self):
        try:
            self._lib.rsched_destroy(self._h)
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass

    # -- membership -------------------------------------------------------

    def add_node(self, resources: Dict[str, float], is_head: bool = False,
                 labels: Optional[dict] = None,
                 node_id: Optional[NodeID] = None) -> NodeID:
        if node_id is None:
            node_id = NodeID.from_random()
        resources = dict(resources)
        resources.setdefault(f"node:{node_id.hex()[:12]}", 1.0)
        if is_head:
            resources.setdefault("node:__internal_head__", 1.0)
        with self._lock:
            handle = self._lib.rsched_add_node(self._h, _encode(resources))
            if handle < 0:
                raise RuntimeError("native add_node failed")
            view = NodeStateView(self, node_id, handle, resources, is_head,
                                 labels)
            self._nodes[node_id] = view
            self._order.append(node_id)
            self._handles[handle] = node_id
        return node_id

    def remove_node(self, node_id: NodeID) -> Optional[NodeStateView]:
        with self._lock:
            view = self._nodes.get(node_id)
            if view is None:
                return None
            if self._lib.rsched_remove_node(self._h, view._handle) != 0:
                return None
            self._order.remove(node_id)
            return view

    def node(self, node_id: NodeID) -> Optional[NodeStateView]:
        return self._nodes.get(node_id)

    def alive_nodes(self) -> List[NodeStateView]:
        with self._lock:
            return [self._nodes[n] for n in self._order]

    def nodes_snapshot(self) -> List[dict]:
        with self._lock:
            out = []
            for node_id, view in self._nodes.items():
                alive = view.alive
                out.append({
                    "NodeID": node_id.hex(),
                    "Alive": alive,
                    "Resources": dict(view.resources),
                    "Available": view.local.available if alive else {},
                    "IsHead": view.is_head,
                    "Labels": dict(view.labels),
                })
            return out

    # -- aggregate views --------------------------------------------------

    @property
    def total(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        for view in self.alive_nodes():
            for k, v in view.local.total.items():
                agg[k] = agg.get(k, 0.0) + v
        return agg

    @property
    def available(self) -> Dict[str, float]:
        agg: Dict[str, float] = {}
        for view in self.alive_nodes():
            for k, v in view.local.available.items():
                agg[k] = agg.get(k, 0.0) + v
        return agg

    # -- selection + accounting -------------------------------------------

    def _affinity_target(self, strategy) -> Optional[NodeStateView]:
        with self._lock:
            for view in self._nodes.values():
                if view.node_id.hex().startswith(strategy.node_id) or \
                        strategy.node_id == view.node_id.hex():
                    return view
        return None

    def is_feasible(self, resources: Dict[str, float],
                    pg_id: Optional[PlacementGroupID] = None,
                    bundle_index: int = -1, strategy=None) -> bool:
        raw = _encode(resources)
        if pg_id is not None:
            with self._lock:
                pg = self._pgs.get(pg_id)
            if pg is None:
                return False
            n = self._lib.rsched_pg_num_bundles(self._h, pg)
            idxs = [bundle_index] if bundle_index >= 0 else range(n)
            for i in idxs:
                if i >= n:
                    return False
                reserved = self._pg_bundle_resources(pg, i, 0)
                if all(reserved.get(k, 0.0) + 1e-9 >= v
                       for k, v in resources.items()):
                    return True
            return False
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        if isinstance(strategy, NodeAffinitySchedulingStrategy) and \
                not strategy.soft:
            target = self._affinity_target(strategy)
            return target is not None and target.alive and bool(
                self._lib.rsched_fits(self._h, target._handle, 0, raw))
        return any(
            self._lib.rsched_fits(self._h, view._handle, 0, raw)
            for view in self.alive_nodes())

    def try_acquire(self, resources: Dict[str, float],
                    pg_id: Optional[PlacementGroupID] = None,
                    bundle_index: int = -1,
                    strategy=None) -> Optional[Tuple[NodeID, int]]:
        raw = _encode(resources)
        if pg_id is not None:
            with self._lock:
                pg = self._pgs.get(pg_id)
            if pg is None:
                return None
            used = self._lib.rsched_pg_try_acquire(self._h, pg,
                                                   bundle_index, raw)
            if used < 0:
                return None
            handle = self._lib.rsched_pg_bundle_node(self._h, pg, used)
            return self._handles.get(handle), used
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy)
        if isinstance(strategy, NodeAffinitySchedulingStrategy):
            target = self._affinity_target(strategy)
            if target is not None and target.alive:
                if self._lib.rsched_try_acquire_on(
                        self._h, target._handle, raw) == 0:
                    return target.node_id, -1
                if not strategy.soft:
                    return None
            elif not strategy.soft:
                return None
            handle = self._lib.rsched_pick_and_acquire(self._h, raw, 0)
            if handle < 0:
                return None
            return self._handles.get(handle), -1
        policy = 1 if strategy == "SPREAD" else 0
        handle = self._lib.rsched_pick_and_acquire(self._h, raw, policy)
        if handle < 0:
            return None
        return self._handles.get(handle), -1

    def release(self, resources: Dict[str, float],
                node_id: Optional[NodeID] = None,
                pg_id: Optional[PlacementGroupID] = None,
                bundle_index: int = -1) -> None:
        raw = _encode(resources)
        if pg_id is not None and bundle_index >= 0:
            with self._lock:
                pg = self._pgs.get(pg_id)
            if pg is not None:
                self._lib.rsched_pg_release(self._h, pg, bundle_index, raw)
            return
        if node_id is None:
            return
        view = self._nodes.get(node_id)
        if view is not None:
            self._lib.rsched_release_on(self._h, view._handle, raw)

    def force_acquire(self, resources: Dict[str, float],
                      node_id: Optional[NodeID] = None,
                      pg_id: Optional[PlacementGroupID] = None,
                      bundle_index: int = -1) -> None:
        raw = _encode(resources)
        if pg_id is not None and bundle_index >= 0:
            with self._lock:
                pg = self._pgs.get(pg_id)
            if pg is not None:
                self._lib.rsched_pg_force_acquire(self._h, pg, bundle_index,
                                                  raw)
            return
        if node_id is None:
            return
        view = self._nodes.get(node_id)
        if view is not None:
            self._lib.rsched_force_acquire_on(self._h, view._handle, raw)

    # -- TPU chip slots ---------------------------------------------------

    def take_tpu_ids(self, node_id: NodeID, n: int) -> Optional[List[int]]:
        with self._lock:
            view = self._nodes.get(node_id)
            if view is None or len(view.free_tpu_ids) < n:
                return None
            return [view.free_tpu_ids.pop() for _ in range(n)]

    def return_tpu_ids(self, node_id: NodeID, ids: List[int]) -> None:
        with self._lock:
            view = self._nodes.get(node_id)
            if view is not None and view.alive:
                view.free_tpu_ids.extend(ids)

    # -- placement groups -------------------------------------------------

    def create_placement_group(self, pg_id: PlacementGroupID,
                               bundles: List[Dict[str, float]],
                               strategy: str = "PACK") -> None:
        encoded = "|".join(_encode(b).decode() for b in bundles).encode()
        code = _PG_STRATEGIES.get(strategy, 0)
        with self._lock:
            if not self._order:
                raise PlacementGroupError("No alive nodes.")
            handle = self._lib.rsched_pg_create(self._h, encoded, code)
            if handle < 0:
                raise PlacementGroupError(
                    f"Placement group bundles {bundles} cannot be reserved "
                    f"with strategy {strategy} on the current cluster "
                    f"(nodes: {[v.local.available for v in self.alive_nodes()]}).")
            self._pgs[pg_id] = handle
            self._pg_strategies[pg_id] = strategy

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        with self._lock:
            handle = self._pgs.pop(pg_id, None)
            self._pg_strategies.pop(pg_id, None)
        if handle is not None:
            self._lib.rsched_pg_remove(self._h, handle)

    def placement_group_exists(self, pg_id: PlacementGroupID) -> bool:
        with self._lock:
            return pg_id in self._pgs

    def _pg_bundle_resources(self, handle: int, bundle: int,
                             which: int) -> Dict[str, float]:
        return _read_encoded(self._lib.rsched_pg_bundle_resources,
                             self._h, handle, bundle, which)

    def placement_groups(self):
        out = {}
        with self._lock:
            items = list(self._pgs.items())
        for pg_id, handle in items:
            n = self._lib.rsched_pg_num_bundles(self._h, handle)
            out[pg_id] = [self._pg_bundle_resources(handle, i, 0)
                          for i in range(n)]
        return out

    def placement_group_table(self) -> List[dict]:
        rows = []
        with self._lock:
            items = list(self._pgs.items())
        for pg_id, handle in items:
            n = self._lib.rsched_pg_num_bundles(self._h, handle)
            bundles = []
            for i in range(n):
                node_handle = self._lib.rsched_pg_bundle_node(self._h,
                                                              handle, i)
                node_id = self._handles.get(node_handle)
                bundles.append({
                    "node_id": node_id.hex() if node_id else None,
                    "resources": self._pg_bundle_resources(handle, i, 0),
                })
            rows.append({
                "placement_group_id": pg_id.hex(),
                "strategy": self._pg_strategies.get(pg_id, "PACK"),
                "bundles": bundles,
            })
        return rows

    def reschedule_lost_bundles(self) -> List[PlacementGroupID]:
        cap = max(len(self._pgs), 1)
        out = (ctypes.c_int64 * cap)()
        count = self._lib.rsched_pg_reschedule_lost(self._h, out, cap)
        touched_handles = {out[i] for i in range(min(count, cap))}
        with self._lock:
            return [pg_id for pg_id, h in self._pgs.items()
                    if h in touched_handles]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "total": self.total,
                "available": self.available,
                "num_nodes": len(self._order),
                "num_placement_groups": len(self._pgs),
            }
