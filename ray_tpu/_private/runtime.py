"""The single-node runtime: task submission, dispatch, execution, actors.

This is the round-1 analog of the reference's CoreWorker + raylet pair
(src/ray/core_worker/core_worker.cc SubmitTask/ExecuteTask;
src/ray/raylet/local_task_manager.cc DispatchScheduledTasksToWorkers):

* ``submit_task`` registers return objects, resolves ObjectRef dependencies
  (callback-driven, like the reference's LocalDependencyResolver), then hands
  the task to the dispatcher.
* The dispatcher acquires resources from the ResourceScheduler and assigns an
  idle executor (worker), growing the pool on demand the way the reference's
  WorkerPool pops/starts workers.
* Actors are executors pinned for the actor's lifetime; actor tasks bypass
  resource accounting and are ordered per submission (serial / threadpool /
  asyncio modes, the analog of the reference's ActorSchedulingQueue +
  ConcurrencyGroupManager fibers).
* Failed tasks retry per ``max_retries``/``retry_exceptions``
  (reference: src/ray/core_worker/task_manager.cc retry path).

Execution backends plug in beneath the executor interface. The default
backend runs tasks on threads in the driver process (JAX/XLA releases the
GIL during compute, so single-host TPU orchestration loses little).
Multi-node runs through the head server + node daemons
(_private/multinode.py).
"""

from __future__ import annotations

import hashlib
import logging
import os
import queue
import threading
import time
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import builtin_metrics, serialization
from ray_tpu._private.cluster_scheduler import (ClusterResourceScheduler,
                                                make_cluster_scheduler)
from ray_tpu._private.ids import (ActorID, JobID, NodeID, ObjectID,
                                  PlacementGroupID, TaskID, WorkerID)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.object_store import ObjectStore
from ray_tpu._private.resource_spec import NodeResources
from ray_tpu._private.task_spec import TaskKind, TaskSpec
from ray_tpu.exceptions import (ActorDiedError, GetTimeoutError,
                                NodeDiedError, ObjectLostError,
                                TaskCancelledError, TaskError)

logger = logging.getLogger("ray_tpu")

_STOP = object()

# Per-thread execution context: which task (if any) this thread is running.
# Used to release the task's resources while it blocks in a nested ``get``
# (the analog of the reference worker's NotifyDirectCallTaskBlocked →
# raylet releases CPU, core_worker.cc).
_task_context = threading.local()


def current_task_spec():
    return getattr(_task_context, "spec", None)


class FunctionTable:
    """Function export table — analog of the reference's FunctionActorManager
    export to GCS KV (python/ray/_private/function_manager.py). Functions are
    pickled once; executors memoize the unpickled callable by id."""

    def __init__(self):
        self._by_id: Dict[bytes, bytes] = {}
        self._loaded: Dict[bytes, Callable] = {}
        self._lock = threading.Lock()

    def export(self, fn: Callable) -> bytes:
        try:
            payload = serialization.dumps_function(fn)
        except Exception:  # noqa: BLE001
            # Unpicklable closure (locks, events, ...): legal on the
            # in-process thread backend where the live object is shared;
            # the process backend would reject this at spawn time.
            payload = None
        if payload is not None:
            fn_id = hashlib.sha1(payload).digest()
        else:
            import os as _os
            fn_id = _os.urandom(20)
        with self._lock:
            if fn_id not in self._by_id:
                if payload is not None:
                    self._by_id[fn_id] = payload
                self._loaded[fn_id] = fn
        return fn_id

    def export_bytes(self, payload: bytes) -> bytes:
        fn_id = hashlib.sha1(payload).digest()
        with self._lock:
            self._by_id.setdefault(fn_id, payload)
        return fn_id

    def get_bytes(self, fn_id: bytes) -> bytes:
        with self._lock:
            return self._by_id[fn_id]

    def load(self, fn_id: bytes) -> Callable:
        with self._lock:
            fn = self._loaded.get(fn_id)
            if fn is not None:
                return fn
            payload = self._by_id[fn_id]
        fn = serialization.loads_function(payload)
        with self._lock:
            self._loaded[fn_id] = fn
        return fn


class _PendingTask:
    __slots__ = ("spec", "unresolved", "cancelled")

    def __init__(self, spec: TaskSpec, unresolved: int):
        self.spec = spec
        self.unresolved = unresolved
        self.cancelled = False


class Executor:
    """A worker: executes submitted thunks. Subclasses define the threading
    model. ``submit`` must preserve submission order for serial executors."""

    def __init__(self, worker_id: WorkerID):
        self.worker_id = worker_id
        self.actor_id: Optional[ActorID] = None
        self.dead = False

    def submit(self, thunk: Callable[[], None]) -> None:
        raise NotImplementedError

    def stop(self, wait: bool = False) -> None:
        raise NotImplementedError


class SerialThreadExecutor(Executor):
    def __init__(self, worker_id: WorkerID, name: str):
        super().__init__(worker_id)
        self._queue: "queue.Queue" = queue.Queue()
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    def _loop(self):
        while True:
            item = self._queue.get()
            if item is _STOP:
                break
            try:
                item()
            except BaseException:  # noqa: BLE001 - executor must survive
                logger.exception("Uncaught error in worker loop")
            # Drop the completed thunk NOW: an idle worker must not keep the
            # last task's spec (and its ObjectRef args) alive until the next
            # task arrives — that pins freed objects' refcounts.
            del item

    def submit(self, thunk):
        self._queue.put(thunk)

    def stop(self, wait: bool = False):
        self.dead = True
        self._queue.put(_STOP)
        if wait:
            self._thread.join(timeout=5)


class ThreadPoolActorExecutor(Executor):
    """Actor executor with max_concurrency > 1 (sync methods)."""

    def __init__(self, worker_id: WorkerID, name: str, max_concurrency: int):
        super().__init__(worker_id)
        import concurrent.futures
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=max_concurrency, thread_name_prefix=name)

    def submit(self, thunk):
        self._pool.submit(thunk)

    def stop(self, wait: bool = False):
        self.dead = True
        self._pool.shutdown(wait=wait, cancel_futures=True)


class ConcurrencyGroupExecutor(Executor):
    """Named concurrency groups for sync actors (reference:
    core_worker/transport/concurrency_group_manager.h): each group gets
    its own sub-executor with its own limit — "io" calls never eat
    "compute" slots — and per-group FIFO ordering holds (serial groups
    are strictly ordered; pooled groups bound concurrency). Untagged
    methods run on the default group (max_concurrency)."""

    def __init__(self, worker_id: WorkerID, name: str,
                 groups: Dict[str, int], max_concurrency: int):
        super().__init__(worker_id)

        def make(limit: int, suffix: str) -> Executor:
            if limit <= 1:
                return SerialThreadExecutor(worker_id, f"{name}-{suffix}")
            return ThreadPoolActorExecutor(worker_id, f"{name}-{suffix}",
                                           limit)

        self._default = make(max(max_concurrency, 1), "default")
        self._groups: Dict[str, Executor] = {
            g: make(int(n), g) for g, n in groups.items()}

    def submit(self, thunk):
        self._default.submit(thunk)

    def submit_group(self, group: Optional[str], thunk):
        self._groups.get(group, self._default).submit(thunk)

    def group_names(self):
        return set(self._groups)

    def stop(self, wait: bool = False):
        self.dead = True
        self._default.stop(wait)
        for ex in self._groups.values():
            ex.stop(wait)


class AsyncioActorExecutor(Executor):
    """Actor executor for async actors: a dedicated event loop thread; each
    task runs as an asyncio task, so ``await`` interleaves calls the way the
    reference's fiber-based async actors do
    (src/ray/core_worker/transport/fiber.h). Named concurrency groups map
    to per-group semaphores on the same loop."""

    def __init__(self, worker_id: WorkerID, name: str, max_concurrency: int,
                 groups: Optional[Dict[str, int]] = None):
        super().__init__(worker_id)
        import asyncio
        self._loop = asyncio.new_event_loop()
        self._sem = asyncio.Semaphore(max_concurrency)
        self._group_sems = {g: asyncio.Semaphore(int(n))
                            for g, n in (groups or {}).items()}
        self._thread = threading.Thread(
            target=self._loop.run_forever, name=name, daemon=True)
        self._thread.start()

    @property
    def loop(self):
        return self._loop

    def submit(self, thunk):
        self.submit_group(None, thunk)

    def submit_group(self, group: Optional[str], thunk):
        import asyncio
        sem = self._group_sems.get(group, self._sem)

        async def _run():
            async with sem:
                result = thunk()
                if asyncio.iscoroutine(result):
                    await result

        asyncio.run_coroutine_threadsafe(_run(), self._loop)

    def stop(self, wait: bool = False):
        import asyncio
        self.dead = True

        def _cancel_then_stop():
            # Cancel parked tasks ON the loop so their cleanup runs here,
            # now, while the runtime is alive — never later in a random
            # thread's garbage collector (long-poll actor methods park
            # for tens of seconds; see _acall's GeneratorExit guard).
            for task in asyncio.all_tasks(self._loop):
                task.cancel()
            self._loop.call_later(0.1, self._loop.stop)

        try:
            self._loop.call_soon_threadsafe(_cancel_then_stop)
        except RuntimeError:
            pass  # loop already closed
        if wait:
            self._thread.join(timeout=5)


class ActorState:
    def __init__(self, actor_id: ActorID, creation_spec: TaskSpec,
                 max_restarts: int, max_concurrency: int, name: str = "",
                 namespace: str = "",
                 concurrency_groups: Optional[Dict[str, int]] = None,
                 lifetime: Optional[str] = None):
        self.actor_id = actor_id
        self.creation_spec = creation_spec
        # Human-readable class name ("Cls" from the creation task's
        # "Cls.__init__") — travels over the actor_info client op so
        # client-session handles can name tasks without loading the class.
        self.class_name = (creation_spec.name or "").rsplit(".", 1)[0]
        self.max_restarts = max_restarts
        self.num_restarts = 0
        self.max_concurrency = max_concurrency
        self.concurrency_groups = dict(concurrency_groups or {})
        self.name = name
        self.namespace = namespace
        # GCS-owned lifetime (reference: gcs_actor_manager detached
        # actors): "detached" actors are NOT reaped on driver exit or
        # client disconnect; only kill(no_restart=True) removes them.
        self.lifetime = lifetime
        self.detached = lifetime == "detached"
        self.executor: Optional[Executor] = None
        self.instance: Any = None  # thread backend: the live instance
        self.dead = False
        self.death_cause: Optional[BaseException] = None
        self.created = threading.Event()
        self.lock = threading.RLock()
        # Per-handle sequencing (the analog of the reference's
        # ActorSchedulingQueue ordering by sequence_no): tasks execute in each
        # handle's submission order even if their deps resolve out of order.
        self.seq_state: Dict[str, dict] = {}
        # Tasks submitted but not yet sealed; killed actors seal these with
        # ActorDiedError so gets never hang.
        self.unfinished: Dict[TaskID, TaskSpec] = {}
        # Dep-resolved tasks that arrived before __init__ finished, in order.
        self.pre_creation_queue: List[TaskSpec] = []
        self.resources_released = False


class _WorkerLease:
    """One worker lease (reference: direct_task_transport.cc:174
    OnWorkerIdle + lease_policy.cc): a single resource acquisition on a
    remote daemon that a stream of same-scheduling-class tasks pipelines
    onto. The daemon runs leased tasks serially on a dedicated executor
    (with a worker subprocess pinned for the lease's lifetime), so one
    acquisition still means one task *running* at a time — the up-to-
    ``max_tasks_in_flight_per_worker`` extras ride the wire early instead
    of paying a head dispatch round-trip each."""

    __slots__ = ("lease_id", "class_key", "node_id", "resources", "pg_id",
                 "bidx", "tpu_ids", "inflight", "dropped", "blocked")

    def __init__(self, lease_id: str, class_key, node_id, resources,
                 pg_id, bidx, tpu_ids):
        self.lease_id = lease_id
        self.class_key = class_key
        self.node_id = node_id
        self.resources = resources
        self.pg_id = pg_id
        self.bidx = bidx
        self.tpu_ids = tpu_ids
        self.inflight = 1  # the creating task
        self.dropped = False
        # COUNT of this lease's tasks blocked in nested gets (the serial
        # task plus any bypass-thread tasks may block simultaneously):
        # while nonzero, skip new attaches and spill the daemon-side
        # queue (deadlock safety — a child queued behind its blocked
        # parent could never run). A boolean cleared on the FIRST
        # unblock re-enabled attaches behind a still-blocked executor.
        # Falsy when 0, so `not lease.blocked` reads stay correct.
        self.blocked = 0


class Runtime:
    def __init__(self, node_resources: NodeResources, job_id: JobID,
                 max_workers: Optional[int] = None,
                 system_config: Optional[Dict[str, Any]] = None,
                 log_to_driver: bool = True):
        import uuid
        self.session_id = uuid.uuid4().hex
        self.job_id = job_id
        self.node_resources = node_resources
        # Typed flag table (reference: RayConfig / ray_config_def.h):
        # native C++ defaults overridable via RAY_TPU_<flag> env vars and
        # the _system_config dict handed to init().
        from ray_tpu._private.ray_config import make_ray_config
        self.config = make_ray_config(system_config)
        # Shared-memory arena sized like the reference's object store
        # (30% of memory, services.py object_store_memory default).
        import tempfile
        spill_dir = (self.config.object_spilling_directory
                     or os.path.join(tempfile.gettempdir(), "ray_tpu_spill",
                                     self.session_id))
        # Durable spill tier (reference: external storage behind the
        # raylet's LocalObjectManager): object_spill_uri routes spill
        # writes through a pluggable backend — session:// / mock-s3://
        # records survive process death and feed tiered recovery. An
        # unset/invalid URI keeps the plain per-session directory.
        spill_backend = None
        _spill_uri = str(self.config.object_spill_uri or "")
        if _spill_uri:
            from ray_tpu._private.spill import backend_for_uri
            try:
                spill_backend = backend_for_uri(
                    _spill_uri, session_id=self.session_id,
                    fallback_dir=spill_dir)
            except (ValueError, OSError):
                logger.exception(
                    "invalid object_spill_uri %r; using the local "
                    "spill directory", _spill_uri)
        self.store = ObjectStore(
            deserializer=serialization.deserialize,
            native_capacity=int(node_resources.memory_bytes *
                                self.config.object_store_memory_fraction),
            use_native=self.config.use_native_object_store,
            spill_threshold_bytes=int(
                self.config.object_spilling_threshold_bytes),
            spill_directory=spill_dir,
            spill_backend=spill_backend)
        # A head-local spilled entry whose file vanished (chaos, scrubbed
        # tmpdir) falls down to the lineage tier instead of surfacing an
        # IO error from get().
        self.store.restore_miss_hook = self._restore_from_lineage
        # Housekeeping: arenas/spill of SIGKILLed predecessors never
        # unlink themselves — a day of test churn measured 118GB of
        # dead /dev/shm mappings starving live runs.
        def _reap_stale():
            from ray_tpu._private.native_store import reap_stale_arenas
            reap_stale_arenas()

        threading.Thread(target=_reap_stale, name="ray_tpu-arena-reaper",
                         daemon=True).start()
        self.scheduler = make_cluster_scheduler(
            use_native=self.config.use_native_scheduler)
        self.head_node_id = self.scheduler.add_node(
            node_resources.to_resource_map(), is_head=True)
        self.functions = FunctionTable()
        self._lock = threading.RLock()
        self._idle_workers: List[Executor] = []
        self._all_workers: List[Executor] = []
        self._ready: List[TaskSpec] = []
        # Leasable NORMAL tasks queue per scheduling class (reference:
        # cluster_task_manager tasks_to_schedule_ by SchedulingClass):
        # same-class tasks are placement-interchangeable, so dispatch
        # probes ONE representative per class instead of scanning every
        # queued task — O(#classes), not O(#tasks), when saturated.
        from collections import deque as _deque
        self._ready_by_class: Dict[Any, Any] = {}
        self._deque = _deque
        self._pending_by_oid: Dict[ObjectID, List[_PendingTask]] = {}
        self._inflight: Dict[TaskID, TaskSpec] = {}
        self._actors: Dict[ActorID, ActorState] = {}
        self._named_actors: Dict[Tuple[str, str], ActorID] = {}
        self._dep_waiters: Dict[ObjectID, threading.Thread] = {}
        self._pg_counter = 0
        self._put_index = 0
        self._shutdown = False
        # Worker cap: thread executors are cheap; cap well above CPU count so
        # blocking tasks (e.g. sleeping) don't starve the pool.
        self._max_workers = max_workers or max(
            int(self.config.worker_cap_min),
            int(node_resources.num_cpus) *
            int(self.config.worker_cap_multiplier))
        self._task_events: List[dict] = []  # lightweight task-event buffer
        self._infeasible_warned: set = set()
        # Real remote node daemons (multi-process cluster, _private/
        # multinode.py): NodeID → NodeConnection. Virtual sim nodes
        # (cluster_utils) never appear here.
        self._remote_nodes: Dict[NodeID, Any] = {}
        self._head_server = None
        # Worker leases (reference: direct_task_transport.cc OnWorkerIdle):
        # class_key -> live leases. Guarded by self._lock.
        self._leases: Dict[Any, List[_WorkerLease]] = {}
        # Attachability index: class_key -> {lease_id: lease} holding
        # only leases with pipeline room (the envelope workload opens
        # THOUSANDS of leases per class — a linear scan per attach was
        # O(leases) on the submit hot path). Maintained by
        # _lease_avail_update at every inflight/blocked/drop mutation;
        # _find_lease double-checks before trusting an entry.
        self._lease_avail: Dict[Any, Dict[str, _WorkerLease]] = {}
        self._lease_counter = 0
        # Compact wire names for scheduling classes (shipped with each
        # leased task so the daemon can group its LOCAL dispatch queues
        # by class; the full class_key is a rich tuple).
        self._class_wire_ids: Dict[Any, str] = {}
        # Class keys dispatch saw feasible-but-capacity-blocked in its
        # last full scan: a draining lease releases early iff a class
        # OTHER than its own is starved (lease fairness without churn).
        self._lease_contended: set = set()
        self.lease_stats = {"created": 0, "attached": 0, "released": 0,
                            "reclaimed": 0}
        self._lease_window = max(
            1, int(self.config.max_tasks_in_flight_per_worker))
        self._lease_enabled = bool(self.config.worker_lease_enabled)
        # Submit/completion hot-path flags, read once: config.get is a
        # native ctypes round-trip — 5 per task adds up at 10k tasks/s.
        self._cfg_inline_limit = int(
            self.config.remote_object_inline_limit_bytes)
        self._cfg_max_task_events = int(self.config.max_task_events)
        self._cfg_lineage_max = int(self.config.lineage_max_entries)
        self._cfg_obj_loc_max = int(
            self.config.object_locations_max_entries)
        self._cfg_locality_spillback = float(
            self.config.locality_spillback_threshold)
        # ObjectID → (NodeID, daemon object key) for results resident on
        # node daemons (fetched lazily; see ObjectStore.put_remote).
        self._remote_values: Dict[ObjectID, Tuple[NodeID, str]] = {}
        # Lineage: creating TaskSpec per return object, for reconstruction
        # after node loss (reference: task_manager.h TaskResubmissionInterface
        # + object_recovery_manager.h). Bounded; puts are not reconstructable.
        self._lineage: Dict[ObjectID, TaskSpec] = {}
        self._object_locations: Dict[ObjectID, NodeID] = {}
        # Tiered-recovery location data (reference: the ownership-based
        # object directory tracking ALL holders, not just the primary):
        # _object_replicas — other daemons known to hold an in-memory
        # copy (learned when a task's marker arg was pulled there);
        # _spill_uris_by_key — durable spill URIs announced by daemons
        # (object_spilled frames), keyed by the daemon object key;
        # _remote_keys — key → ObjectID reverse map for those frames.
        # Node death walks replica → spill → lineage, cheapest first.
        self._object_replicas: Dict[ObjectID, Dict[NodeID, None]] = {}
        self._spill_uris_by_key: Dict[str, Tuple[str, int]] = {}
        self._remote_keys: Dict[str, ObjectID] = {}
        # Collective dataplane (tree broadcast): objects already pushed
        # through a spanning tree (head-resident ones keep materialized
        # values yet still ship as replica markers — see _resolve_args),
        # distinct consumer nodes seen per object (the auto-broadcast
        # demand signal), and the in-flight guard so demand spikes fire
        # one tree, not one per queued pull.
        self._broadcasted: Dict[ObjectID, None] = {}
        self._pull_demand: Dict[ObjectID, Dict[NodeID, None]] = {}
        self._broadcast_inflight: Dict[ObjectID, None] = {}
        # Ownership/reference counting (reference: reference_count.h):
        # ObjectRef handles hold local refs, pending tasks hold dependency
        # refs; when an owned object's counts hit zero its value is freed
        # and lineage pruned. Native C++ engine with a Python twin.
        from ray_tpu._private.refcount import make_reference_counter
        self.refs = make_reference_counter(
            use_native=self.config.use_native_refcount)
        # Long-poll pubsub hub (reference: src/ray/pubsub/): task-state
        # events publish here; consumers subscribe + poll.
        from ray_tpu._private.pubsub import make_pubsub
        self.pubsub = make_pubsub()
        self._chaos_us = {
            flag: int(self.config.get(flag))
            for flag in ("testing_submit_delay_us",
                         "testing_dispatch_delay_us",
                         "testing_store_delay_us")
        }
        # OOM protection (reference: MemoryMonitor + worker-killing policy):
        # poll memory pressure; above the threshold, fail the newest
        # retriable running task.
        self.memory_monitor = None
        threshold = float(self.config.memory_usage_threshold)
        refresh_ms = int(self.config.memory_monitor_refresh_ms)
        if 0 < threshold < 1.0 and refresh_ms > 0:
            from ray_tpu._private.memory_monitor import MemoryMonitor
            self.memory_monitor = MemoryMonitor(
                threshold, refresh_ms,
                get_running_tasks=self._running_normal_tasks,
                kill_fn=self._oom_kill_task)
            self.memory_monitor.start()
        # Process worker pool (reference: raylet WorkerPool — real worker
        # subprocesses). Lazily created: tasks/actors opt in via
        # runtime_env {"worker_process": True} (or pip/venv envs); TPU
        # tasks always run in this chip-owning process.
        self._process_pool = None
        self._proc_tasks: Dict[TaskID, Any] = {}  # task_id → WorkerHandle
        # GCS persistence (reference: gcs_server.cc:523 Redis-backed
        # storage): with _system_config={"gcs_store_path": ...}, the
        # internal KV + named-actor + job tables survive head death; a
        # restarted head restores them and rebinds daemon-resident
        # actors as their daemons reconnect.
        self.gcs_store = None
        self._kv_mem: Dict[str, Dict[bytes, bytes]] = {}
        gcs_path = str(self.config.gcs_store_path or "")
        if gcs_path:
            from ray_tpu._private.gcs_store import GcsStore
            self.gcs_store = GcsStore(gcs_path)
            # Job table (reference: GcsJobManager): the driver's job
            # record survives head death, so a post-restart head can
            # answer "what ran here". Keyed process-uniquely: JobID is a
            # per-process counter, so two driver processes sharing a
            # store would otherwise clobber each other's records.
            import uuid as _uuid
            self._gcs_job_key = f"{job_id.hex()}-{_uuid.uuid4().hex[:8]}"
            self.gcs_store.record_job(self._gcs_job_key, {
                "job_id": job_id.hex(),
                "pid": os.getpid(),
                "status": "RUNNING",
                "start_time": time.time(),
            })
        # Fenced membership (wire v9, _private/membership.py): every
        # daemon registration mints an incarnation epoch here; the
        # HeadServer's suspicion loop and death paths declare through
        # this table (exactly-once per incarnation), and join/death
        # events fan out to in-process subscribers (serve controller,
        # train executor) plus the "membership" pubsub channel.
        from ray_tpu._private.membership import MembershipTable
        self.membership = MembershipTable(self.gcs_store)
        self.membership.subscribe(self._membership_event)
        # Head failover (reference: GCS server restart replaying its
        # persistent store before serving): when the store carries a
        # previous head life's state, rehydrate the control plane NOW —
        # before the head server accepts any daemon traffic. Membership
        # already floored its epoch counter above every prior epoch;
        # here the object directory's durable tiers come back, dead
        # serve-generation actor records are retired (the fresh
        # controller redeploys from the serve table instead), and the
        # head incarnation counter + recovery summary land back in the
        # store for status surfaces.
        self._head_incarnation = 0
        self._head_recovery: Optional[Dict[str, Any]] = None
        self._recovered_object_replicas: Dict[str, list] = {}
        self._serve_rehydrate_started = False
        if self.gcs_store is not None:
            self._recover_from_store()
        # Deferred-free queue: ObjectRef.__del__ can fire at any point —
        # including inside the store's non-reentrant lock when a freed value
        # drops the last handle to another object — so handle-death frees
        # are drained by a dedicated GC thread instead of inline.
        import collections
        self._gc_queue: "collections.deque[ObjectID]" = collections.deque()
        self._gc_event = threading.Event()
        self._gc_thread = threading.Thread(
            target=self._gc_loop, name="ray_tpu-refgc", daemon=True)
        self._gc_thread.start()
        # Log subsystem (reference: _private/log_monitor.py + worker.py
        # print_logs): head-spawned worker output is captured to session
        # files and tailed by a head-local LogMonitor; daemons push
        # log_batch frames for theirs; everything fans out on the "logs"
        # pubsub channel, where a printer thread echoes it to the
        # driver's console unless init(log_to_driver=False).
        self.log_to_driver = log_to_driver
        self._log_monitor = None
        self._log_printer = None
        from ray_tpu._private import ray_logging
        try:
            ray_logging.setup_session(self.session_id, "head")
        except OSError:
            logger.exception("could not create the session log dir; "
                             "worker output will inherit this console")
        else:
            from ray_tpu._private.log_monitor import LogMonitor
            self._log_monitor = LogMonitor(self._publish_log_batch)
            ray_logging.register_capture_callback(
                self._log_monitor.add_file)
            if log_to_driver:
                self._log_printer = ray_logging.DriverLogPrinter(
                    self.pubsub)
        # Cluster metrics pipeline (reference: dashboard/agent.py + the
        # core's metric_exporter, collapsed to ONE scrape): the head
        # holds the cluster registry; its own agent publishes this
        # process's series straight into it, daemons and workers arrive
        # as metrics_batch frames / reply piggybacks.
        from ray_tpu._private.metrics_agent import (ClusterMetrics,
                                                    MetricsAgent)
        self._cluster_metrics = ClusterMetrics()
        self._journal_head_recovery()
        self._metrics_agent = MetricsAgent(
            self._publish_head_metrics, component="driver",
            publish_profile=self._publish_head_profile,
            publish_flow=self._publish_head_flow)
        self._metrics_agent.add_collector(self._collect_head_metrics)

    # ------------------------------------------------------------------
    # Head failover recovery
    # ------------------------------------------------------------------

    def _recover_from_store(self) -> None:
        """Rehydrate head state from the gcs_store before serving.

        Runs in __init__, before start_head_server can accept a single
        daemon — so everything a re-registering daemon's handshake
        touches (epoch floor, actor records, object directory) is
        already in its recovered shape. Replayed tiers:

        * spill URIs — durable by definition (the bytes live in the
          spill dir, not in any process), so they go straight back into
          the live ``_spill_uris_by_key`` table and tiered recovery can
          restore from them immediately.
        * replica holders — node ids are re-minted when daemons
          re-register, so the recorded NodeID hexes are stale; they are
          kept in a side table for status/debugging only, never in the
          live ``_object_replicas`` map.
        * serve actor records — controller/replica actors belong to the
          dead head's serve generation; their records are dropped so
          re-registering daemons don't rebind zombies (the daemon
          destroys them instead) and the fresh controller redeploys
          from the durable serve table.
        """
        store = self.gcs_store
        counts = store.counts()
        recovery: Optional[Dict[str, Any]] = None
        if store.had_prior_state:
            # Spill URIs: live again immediately.
            spills = dict(store.spill_uris)
            self._spill_uris_by_key.update(spills)
            # Replica holders: stale node identities → side table only.
            self._recovered_object_replicas = {
                k: list(v) for k, v in store.object_replicas.items()}
            # Serve-generation actors died with the old head; retire
            # their records (detached *user* actors keep theirs — that
            # is the exactly-once incarnation guarantee).
            purged = [aid for aid, rec in list(store.actors.items())
                      if str(rec.get("name") or "").startswith(
                          ("_serve_controller", "_serve_replica::"))]
            for aid in purged:
                store.remove_actor(aid)
            recovery = {
                "at": time.time(),
                "epoch_floor": self.membership.recovered_epoch_floor,
                "corrupt_records": store.corrupt_records,
                "replayed": {
                    "kv": counts["kv"],
                    "actors": counts["actors"] - len(purged),
                    "jobs": counts["jobs"],
                    "node_epochs": counts["node_epochs"],
                    "serve_deployments": counts["serve_deployments"],
                    "spill_uris": len(spills),
                    "object_replicas": len(
                        self._recovered_object_replicas),
                },
            }
        else:
            self._recovered_object_replicas = {}
        self._head_incarnation = store.begin_head_incarnation(recovery)
        self._head_recovery = recovery
        if recovery is not None:
            try:
                from ray_tpu._private import builtin_metrics
                builtin_metrics.head_recoveries().inc()
                for kind, n in recovery["replayed"].items():
                    if n:
                        builtin_metrics.head_recovery_replayed().inc(
                            n, tags={"kind": kind})
            except Exception:  # noqa: BLE001 - metrics must not block boot
                logger.exception("head recovery metrics failed")
            logger.warning(
                "head recovered from gcs_store %s: incarnation %d, "
                "epoch floor %d, replayed %s (%d corrupt records "
                "skipped)", store.path, self._head_incarnation,
                recovery["epoch_floor"], recovery["replayed"],
                recovery["corrupt_records"])

    def head_recovery_info(self) -> Dict[str, Any]:
        """Status surface: head incarnation + last recovery summary."""
        info: Dict[str, Any] = {
            "incarnation": self._head_incarnation,
            "recovered": self._head_recovery is not None,
            "last_recovery": self._head_recovery,
            "prior_node_count": getattr(
                self.membership, "prior_node_count", 0),
        }
        return info

    def _journal_head_recovery(self) -> None:
        """Emit the ``head_recovered`` journal event. Called from
        __init__ right after the cluster journal exists (the recovery
        itself ran earlier, before any daemon traffic)."""
        rec = self._head_recovery
        if rec is None:
            return
        labels = {"incarnation": str(self._head_incarnation),
                  "epoch_floor": str(rec["epoch_floor"])}
        labels.update({f"replayed_{k}": str(v)
                       for k, v in rec["replayed"].items() if v})
        try:
            self._cluster_metrics.events.record(
                "head", "head_recovered", severity="warning",
                labels=labels)
        except Exception:  # noqa: BLE001 - journal is best-effort
            logger.exception("could not journal head recovery")

    def maybe_rehydrate_serve_async(self) -> None:
        """Redeploy persisted serve applications in the background.

        Triggered once per runtime, after the worker wiring is attached
        (deploys go through the normal actor API). The controller's
        deploy retry budget absorbs daemons that re-register after us:
        a replica needing a daemon's resources just stays pending until
        that daemon's resources come back."""
        if self.gcs_store is None or self._serve_rehydrate_started:
            return
        if not self.gcs_store.serve_deployments:
            return
        self._serve_rehydrate_started = True
        t = threading.Thread(target=self._rehydrate_serve,
                             name="ray_tpu-serve-rehydrate", daemon=True)
        t.start()

    def _rehydrate_serve(self) -> None:
        try:
            from ray_tpu.serve import _redeploy_from_records
            records = dict(self.gcs_store.serve_deployments)
            n = _redeploy_from_records(records)
            if n:
                logger.warning(
                    "serve rehydrated %d deployment(s) from gcs_store",
                    n)
                try:
                    self._cluster_metrics.events.record(
                        "serve", "serve_rehydrated", severity="info",
                        labels={"deployments": str(n)})
                except Exception:  # noqa: BLE001
                    pass
        except Exception:  # noqa: BLE001 - rehydration is best-effort;
            # the deployments stay in the store for the next attempt.
            logger.exception("serve rehydration failed")

    # ------------------------------------------------------------------
    # Object API
    # ------------------------------------------------------------------

    def free_objects(self, oids: List[ObjectID]) -> None:
        """Explicitly free object values (``ray.free`` analog) regardless of
        outstanding references, cascading to objects contained in them."""
        cascade: Dict[ObjectID, None] = dict.fromkeys(oids)
        for oid in oids:
            # force_free returns the oid itself (when tracked) plus any
            # contained objects it cascaded to; dedupe against the explicit
            # list so nothing reaches store.free twice.
            cascade.update(dict.fromkeys(self.refs.force_free(oid)))
        self._free_now(list(cascade))

    def _free_now(self, oids: List[ObjectID]) -> None:
        """Drop freed objects' values and lineage/location bookkeeping (the
        reference prunes lineage when refs go out of scope)."""
        if not oids:
            return
        self.store.free(oids)
        remote_frees = []
        had_spill_uri = []
        with self._lock:
            all_conns = list(self._remote_nodes.values())
            for oid in oids:
                self._lineage.pop(oid, None)
                self._object_locations.pop(oid, None)
                self._object_replicas.pop(oid, None)
                self._broadcasted.pop(oid, None)
                self._pull_demand.pop(oid, None)
                self._broadcast_inflight.pop(oid, None)
                rv = self._remote_values.pop(oid, None)
                if rv is not None:
                    remote_frees.append(rv[1])
                    self._remote_keys.pop(rv[1], None)
                    if self._spill_uris_by_key.pop(rv[1], None) \
                            is not None:
                        had_spill_uri.append(rv[1])
        # Retract the durable object-directory mirror (throttled saves
        # inside the store: a mass free coalesces to one fsync).
        if self.gcs_store is not None:
            try:
                for key in had_spill_uri:
                    self.gcs_store.remove_spill_uri(key)
                for oid in oids:
                    self.gcs_store.remove_object_replicas(oid.hex())
            except OSError:
                pass
        # Broadcast: peer daemons may hold PULLED copies of the object
        # beyond the primary (the data plane caches pulls locally), so
        # every node gets the eviction notice (reference: object pubsub
        # eviction notifications).
        for key in remote_frees:
            for conn in all_conns:
                try:
                    conn.free_object(key)
                except Exception:  # noqa: BLE001 - best effort
                    pass

    def on_ref_deleted(self, oid: ObjectID) -> None:
        """An ObjectRef handle was garbage collected. Runs inside __del__,
        which can fire at ANY allocation (cyclic GC) — including while this
        very thread holds the store lock, the reference counter's lock, or
        even the GC event's internal (non-reentrant) condition lock. So:
        strictly lock-free here — deque.append only; the GC thread's timed
        poll (gc_sweep_interval_ms) picks the oid up."""
        self._gc_queue.append(oid)

    def _gc_loop(self) -> None:
        while True:
            self._gc_event.wait(
                timeout=self.config.gc_sweep_interval_ms / 1000.0)
            self._gc_event.clear()
            batch: List[ObjectID] = []
            while self._gc_queue:
                try:
                    batch.append(self._gc_queue.popleft())
                except IndexError:
                    break
            if batch:
                try:
                    freed: List[ObjectID] = []
                    for oid in batch:
                        freed.extend(self.refs.remove_local(oid))
                    self._free_now(freed)
                except Exception:  # noqa: BLE001 - GC must never die
                    logger.exception("refcount GC sweep failed")
            if self._shutdown:
                # Exit promptly (don't wait for the queue to drain): the
                # whole store is being torn down, and shutdown() joins this
                # thread before unmapping the native arena.
                return

    def _register_task_refs(self, spec: TaskSpec) -> None:
        """Owner-side bookkeeping at submission: own the return objects and
        pin the argument objects until the task completes."""
        if spec.num_returns != 0:
            for oid in spec.return_ids:
                self.refs.add_owned(oid)
        deps = self._find_dependencies(spec)
        spec._dep_oids = deps  # type: ignore[attr-defined]
        self.refs.add_task_deps(deps)

    def _release_task_deps(self, spec: TaskSpec) -> None:
        """Task reached a terminal state: drop its dependency pins.
        Atomic: a completing worker and a killer (OOM / node death) may
        race here; exactly one release happens."""
        with self._lock:
            deps = getattr(spec, "_dep_oids", None)
            spec._dep_oids = None  # type: ignore[attr-defined]
        if deps:
            self._free_now(self.refs.remove_task_deps(deps))

    def put(self, value: Any) -> ObjectRef:
        with self._lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(TaskID.for_normal_task(self.job_id), idx)
        self._chaos_delay("testing_store_delay_us")
        self.store.put_inline(oid, value)
        self.refs.add_owned(oid)
        return ObjectRef(oid)

    def create_promise(self) -> ObjectRef:
        """Mint an owned but UNSEALED object (a promise): ``get`` blocks
        until someone settles it via :meth:`fulfill_promise`. The serve
        router hands these to callers so the caller-visible ref survives
        replica failover — the ref's identity is decoupled from any one
        actor-task attempt (reference: serve router replica_result
        wrappers over retried assignments)."""
        with self._lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(TaskID.for_normal_task(self.job_id), idx)
        self.store._entry(oid)  # create the unsealed entry now
        self.refs.add_owned(oid)
        return ObjectRef(oid)

    def fulfill_promise(self, ref: ObjectRef, value: Any = None,
                        exception: Optional[BaseException] = None,
                        alias: Optional[ObjectRef] = None) -> None:
        """Settle a promise minted by :meth:`create_promise`.

        Exactly one of ``value`` / ``exception`` / ``alias`` semantics
        applies; the store's first-write-wins seal makes racing settles
        (e.g. a deadline expiry vs. a completing replica) safe. With
        ``alias`` the promise resolves to whatever the alias ref holds,
        materialized lazily through the store's remote-fetch hook: the
        closure pins the alias ref until the value (or error) is read."""
        oid = ref.object_id()
        if alias is not None:
            inner = alias  # closure keeps the aliased ref (and oid) alive

            def _fetch(timeout=None):
                return self.store.get(inner.object_id(), timeout=timeout)

            self.store.put_remote(oid, _fetch, 0)
        elif exception is not None:
            self.store.put_inline(oid, exception, is_exception=True)
        else:
            self.store.put_inline(oid, value)

    def register_remote_put(self, node_id: NodeID, key: str,
                            size: int, adopt: bool) -> ObjectRef:
        """Distributed-ownership put: the VALUE already sits in
        ``node_id``'s object table (written by daemon- or worker-side
        user code); the head records only the DIRECTORY entry and mints
        the ref (reference: owner-is-creator, reference_count.h:61 —
        the creating node serves the bytes; losing that node loses the
        object, exactly the reference's owner-failure model). ``adopt``
        asks the daemon to take bookkeeping ownership first (worker-
        process writers bypass the daemon's table accounting)."""
        conn = self._remote_nodes.get(node_id)
        if conn is None:
            raise KeyError(f"node {node_id.hex()[:12]} is not connected")
        if adopt and not conn.adopt_object(key, size):
            raise KeyError(
                f"object {key} no longer resident on "
                f"{node_id.hex()[:12]} (evicted before adoption)")
        with self._lock:
            self._put_index += 1
            idx = self._put_index
        oid = ObjectID.for_put(TaskID.for_normal_task(self.job_id), idx)
        from ray_tpu._private.multinode import RemoteValueStub
        stub = RemoteValueStub(conn, key, size)
        with self._lock:
            self._remote_values[oid] = (node_id, key)
        self.store.put_remote(oid, stub.fetch, size)
        self.refs.add_owned(oid)
        return ObjectRef(oid)

    def get(self, refs: List[ObjectRef], timeout: Optional[float]) -> List[Any]:
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        # If a worker thread blocks here on objects that aren't ready yet,
        # release its task's resources so dependent/nested tasks can run
        # (otherwise a parent holding the only CPU deadlocks on its child).
        blocking = any(not self.store.contains(r.object_id()) for r in refs)
        spec = current_task_spec() if blocking else None
        released = False
        if spec is not None and spec.resources:
            pg_id, _ = self._pg_key(spec)
            node_id = getattr(spec, "_node_id", None)
            bidx = getattr(spec, "_acquired_bundle", -1)
            self.scheduler.release(spec.resources, node_id, pg_id, bidx)
            released = True
            self._dispatch()
        try:
            results = []
            for ref in refs:
                remaining = None
                if deadline is not None:
                    remaining = max(0.0, deadline - _time.monotonic())
                results.append(self.store.get(ref.object_id(), timeout=remaining))
            return results
        finally:
            if released:
                self.scheduler.force_acquire(
                    spec.resources, node_id, pg_id, bidx)

    def wait(self, refs: List[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool = True):
        import time as _time
        deadline = None if timeout is None else _time.monotonic() + timeout
        pending = list(refs)
        ready: List[ObjectRef] = []
        # Fast path scan, then block on the first pending ref repeatedly.
        while len(ready) < num_returns and pending:
            progressed = False
            for ref in list(pending):
                if self.store.contains(ref.object_id()):
                    ready.append(ref)
                    pending.remove(ref)
                    progressed = True
                    if len(ready) >= num_returns:
                        break
            if len(ready) >= num_returns or not pending:
                break
            if not progressed:
                remaining = 0.05
                if deadline is not None:
                    remaining = min(remaining,
                                    max(0.0, deadline - _time.monotonic()))
                    if remaining == 0.0:
                        break
                self.store.wait_ready(pending[0].object_id(), remaining)
                if deadline is not None and _time.monotonic() >= deadline:
                    # final scan before giving up
                    for ref in list(pending):
                        if self.store.contains(ref.object_id()):
                            ready.append(ref)
                            pending.remove(ref)
                            if len(ready) >= num_returns:
                                break
                    break
        return ready, pending

    # ------------------------------------------------------------------
    # Task submission
    # ------------------------------------------------------------------

    def register_function(self, fn: Callable) -> bytes:
        return self.functions.export(fn)

    def _chaos_delay(self, flag: str) -> None:
        """Fault-injection hook (reference: asio_chaos.cc +
        RAY_testing_asio_delay_us): sleep testing_*_delay_us microseconds
        when the flag is nonzero, to surface ordering races in tests.
        Values are snapshotted at init — submit/dispatch are hot paths, and
        a per-call native config probe there is not free."""
        us = self._chaos_us.get(flag, 0)
        if us:
            import time as _time
            _time.sleep(us / 1e6)

    def submit_task(self, spec: TaskSpec) -> List[ObjectRef]:
        """Submit a normal task. Returns refs for its return objects."""
        self._chaos_delay("testing_submit_delay_us")
        from ray_tpu.util import tracing
        if tracing.is_tracing_enabled():
            # Propagate the caller's span context inside the spec
            # (reference: tracing_helper.py _DictPropagator). With no
            # active caller span this is the HEAD of a trace:
            # inject_context makes the sampling decision once, and an
            # unsampled submit carries no context at all.
            ctx = tracing.inject_context()
            if ctx is not None:
                import time as _time
                with tracing.continue_context(
                        ctx, "driver::submit",
                        {"stage": "submit", "task": spec.name}) as span:
                    spec.trace_ctx = tracing.span_context(span)
                    spec._trace_submit_mono = _time.monotonic()  # type: ignore[attr-defined]
                    spec._trace_submit_wall = span.start_time  # type: ignore[attr-defined]
                    return self._submit_task_inner(spec)
        return self._submit_task_inner(spec)

    def _submit_task_inner(self, spec: TaskSpec) -> List[ObjectRef]:
        n = 1 if spec.num_returns == "dynamic" else spec.num_returns
        spec.return_ids = [
            ObjectID.for_return(spec.task_id, i + 1) for i in range(max(n, 1))]
        refs = [ObjectRef(oid) for oid in spec.return_ids]
        if spec.num_returns == 0:
            refs = []
        with self._lock:
            if len(self._lineage) < self._cfg_lineage_max:
                for oid in spec.return_ids:
                    self._lineage[oid] = spec
        self._register_task_refs(spec)
        self._record_event(spec, "SUBMITTED")
        self._resolve_dependencies(spec)
        return refs

    def _find_dependencies(self, spec: TaskSpec) -> List[ObjectID]:
        deps = []
        for a in spec.args:
            if isinstance(a, ObjectRef):
                deps.append(a.object_id())
        for v in spec.kwargs.values():
            if isinstance(v, ObjectRef):
                deps.append(v.object_id())
        return deps

    def _resolve_dependencies(self, spec: TaskSpec) -> None:
        # _register_task_refs already walked the args; reuse its list.
        deps = getattr(spec, "_dep_oids", None)
        if deps is None:
            deps = self._find_dependencies(spec)
        spec.dependencies = deps
        unresolved = [d for d in deps if not self.store.contains(d)]
        if not unresolved:
            self._on_dependencies_ready(spec)
            return
        pending = _PendingTask(spec, 0)
        to_watch = []
        with self._lock:
            # Count + registration both under the lock: a concurrent seal's
            # waiter can only decrement entries registered here, so the
            # zero-check below cannot race with a waiter's decrement.
            for d in unresolved:
                if self.store.contains(d):
                    continue
                pending.unresolved += 1
                self._pending_by_oid.setdefault(d, []).append(pending)
                to_watch.append(d)
            ready_now = pending.unresolved == 0
        if ready_now:
            self._on_dependencies_ready(spec)
            return
        # Watch each unresolved dep from a waiter thread; cheap enough at
        # round-1 scale, replaced by store callbacks with the native store.
        for d in to_watch:
            self._spawn_dep_waiter(d)

    def _spawn_dep_waiter(self, oid: ObjectID) -> None:
        with self._lock:
            if oid in self._dep_waiters:
                return
            t = threading.Thread(
                target=self._dep_wait_loop, args=(oid,), daemon=True)
            self._dep_waiters[oid] = t
        t.start()

    def _dep_wait_loop(self, oid: ObjectID) -> None:
        self.store.wait_ready(oid, None)
        ready = []
        with self._lock:
            self._dep_waiters.pop(oid, None)
            waiters = self._pending_by_oid.pop(oid, [])
            for pending in waiters:
                pending.unresolved -= 1
                if pending.unresolved == 0 and not pending.cancelled:
                    ready.append(pending.spec)
        for spec in ready:
            try:
                self._on_dependencies_ready(spec)
            except BaseException as e:  # noqa: BLE001 - keep waiter alive
                self._store_error(spec, e)

    def _on_dependencies_ready(self, spec: TaskSpec) -> None:
        # Propagate dependency failures without running the task
        # (reference behavior: dependent tasks fail with the same error).
        for d in spec.dependencies:
            exc = self.store.get_if_exception(d)
            if exc is not None:
                self._store_error(spec, exc)
                if spec.kind == TaskKind.ACTOR_TASK:
                    # The handle's sequence must still advance, or every
                    # later call on this handle would wait forever.
                    self._abort_actor_task_seq(spec)
                return
        if spec.kind == TaskKind.ACTOR_TASK:
            self._dispatch_actor_task(spec)
        else:
            self._dispatch_single(spec)

    # ------------------------------------------------------------------
    # Dispatch
    # ------------------------------------------------------------------

    def _pg_key(self, spec: TaskSpec):
        strategy = spec.scheduling_strategy
        pg_id = None
        bundle = -1
        if strategy is not None and hasattr(strategy, "placement_group") and \
                strategy.placement_group is not None:
            pg_id = strategy.placement_group.id
            bundle = strategy.placement_group_bundle_index
            if bundle is None:
                bundle = -1
        return pg_id, bundle

    # ------------------------------------------------------------------
    # Worker leases (reference: direct_task_transport.cc + lease_policy)
    # ------------------------------------------------------------------

    def _lease_class(self, spec: TaskSpec):
        """Scheduling class for worker leasing (reference:
        scheduling_class_util): tasks sharing one are placement-
        interchangeable and may pipeline onto one lease. None means the
        task is not leasable (actors, affinity/spread strategies — those
        carry per-task placement intent)."""
        key = getattr(spec, "_lease_key", False)
        if key is not False:
            return key
        key = None
        if self._lease_enabled and spec.kind == TaskKind.NORMAL:
            strategy = spec.scheduling_strategy
            pg_id, bundle = self._pg_key(spec)
            if strategy is None or strategy == "DEFAULT" or pg_id is not None:
                try:
                    renv = repr(sorted((spec.runtime_env or {}).items()))
                    res = tuple(sorted((spec.resources or {}).items()))
                    key = (spec.function_id, res, renv, pg_id, bundle)
                except TypeError:
                    key = None
        spec._lease_key = key  # type: ignore[attr-defined]
        return key

    def _lease_attachable(self, lease: _WorkerLease) -> bool:
        return (not lease.dropped and not lease.blocked
                and lease.inflight < self._lease_window
                and lease.node_id in self._remote_nodes)

    def _lease_avail_update(self, lease: _WorkerLease) -> None:
        """Re-index one lease's attachability (caller holds _lock)."""
        bucket = self._lease_avail.get(lease.class_key)
        if self._lease_attachable(lease):
            if bucket is None:
                bucket = self._lease_avail[lease.class_key] = {}
            bucket[lease.lease_id] = lease
        elif bucket is not None:
            bucket.pop(lease.lease_id, None)
            if not bucket:
                del self._lease_avail[lease.class_key]

    def _find_lease(self, class_key) -> Optional[_WorkerLease]:
        """An attachable live lease for this class (caller holds _lock).
        O(1) amortized via the availability index: peek the head entry,
        pop it if stale (safe — every indexed mutation re-adds through
        _lease_avail_update). No bucket copy: materializing thousands
        of entries per attach would re-create the linear scan this
        index removed."""
        bucket = self._lease_avail.get(class_key)
        while bucket:
            lease_id, lease = next(iter(bucket.items()))
            if self._lease_attachable(lease):
                return lease
            bucket.pop(lease_id, None)
        if bucket is not None and not bucket:
            del self._lease_avail[class_key]
        return None

    def _lease_task_done(self, spec: TaskSpec, lease: _WorkerLease) -> None:
        """Completion bookkeeping for a leased task. A lease that drains
        either TAKES the next queued same-class task right here (so a
        kept-alive lease always has a completion coming to re-evaluate
        it — a passively "kept" idle lease would leak its resources if
        the queued work later launched elsewhere or was cancelled) or
        drops and releases. Contention from OTHER classes forces the
        drop, so starved classes get the scheduler's arbitration."""
        drop = False
        next_spec = None
        with self._lock:
            lease.inflight -= 1
            self._lease_avail_update(lease)
            if lease.dropped:
                return  # node death already tore it down
            if lease.inflight <= 0:
                starved_other = any(k != lease.class_key
                                    for k in self._lease_contended)
                dq = self._ready_by_class.get(lease.class_key)
                if dq and not starved_other and not lease.blocked and \
                        lease.node_id in self._remote_nodes:
                    next_spec = dq.popleft()
                    if not dq:
                        del self._ready_by_class[lease.class_key]
                    self._inflight[next_spec.task_id] = next_spec
                    next_spec._node_id = lease.node_id
                    next_spec._acquired_bundle = lease.bidx
                    next_spec._lease = lease  # type: ignore[attr-defined]
                    next_spec._tpu_ids = lease.tpu_ids
                    lease.inflight += 1
                    self._lease_avail_update(lease)
                    next_spec.invalidated = False
                    next_spec._finalized = False
                    self.lease_stats["attached"] += 1
                else:
                    lease.dropped = True
                    self._lease_avail_update(lease)
                    lst = self._leases.get(lease.class_key)
                    if lst is not None:
                        try:
                            lst.remove(lease)
                        except ValueError:
                            pass
                        if not lst:
                            del self._leases[lease.class_key]
                    drop = True
        if next_spec is not None:
            self._launch(next_spec, None)
            return
        if drop:
            self.scheduler.release(lease.resources, lease.node_id,
                                   lease.pg_id, lease.bidx)
            if lease.tpu_ids:
                self.scheduler.return_tpu_ids(lease.node_id, lease.tpu_ids)
            self.lease_stats["released"] += 1
            conn = self._remote_nodes.get(lease.node_id)
            if conn is not None:
                conn.drop_lease(lease.lease_id)
            # Freed capacity + empty head-side queues: pull misplaced
            # work back from overloaded daemon queues.
            self._maybe_spillback()

    def _class_wire_id(self, class_key) -> str:
        """Compact stable name for a scheduling class, shipped on the
        wire so daemons key their local dispatch queues by it."""
        with self._lock:
            cid = self._class_wire_ids.get(class_key)
            if cid is None:
                cid = f"k{len(self._class_wire_ids)}"
                self._class_wire_ids[class_key] = cid
            return cid

    def _maybe_spillback(self) -> None:
        """Misplaced-work correction (reference: cluster_task_manager.cc
        ScheduleAndDispatchTasks spillback): capacity just freed
        somewhere, the head has nothing queued for a class, yet tasks
        already pipelined to one node sit in its LOCAL queue behind busy
        slots. Reclaim the tail; the re-dispatch takes the idle
        capacity. Only non-PG, non-TPU (shared-queue) classes — serial
        leases keep strict ownership of their pipelined tasks."""
        target = None
        with self._lock:
            for ck, leases in self._leases.items():
                if self._ready_by_class.get(ck):
                    continue  # new capacity will be fed head-side
                for lease in leases:
                    if (lease.pg_id is not None or lease.tpu_ids
                            or lease.blocked or lease.dropped):
                        continue
                    extra = lease.inflight - 1
                    if extra >= 2 and (target is None
                                       or extra > target[2]):
                        target = (ck, lease, extra)
        if target is None:
            return
        ck, lease, extra = target
        # Probe: does idle capacity for this class actually exist? (The
        # probe acquisition is returned immediately — the reclaimed
        # tasks re-acquire through the normal dispatch path.)
        acq = self.scheduler.try_acquire(lease.resources, None, -1)
        if acq is None:
            return
        self.scheduler.release(lease.resources, acq[0], None, acq[1])
        conn = self._remote_nodes.get(lease.node_id)
        if conn is not None:
            conn.reclaim_tasks(self._class_wire_id(ck),
                               max_n=min(extra, 16))

    def _drop_node_leases(self, node_id: NodeID) -> None:
        """Node death: its leases vanish with it — the scheduler already
        dropped the node's resources wholesale, so no release here."""
        with self._lock:
            for key in list(self._leases):
                lst = self._leases[key]
                for lease in lst[:]:
                    if lease.node_id == node_id:
                        lease.dropped = True
                        self._lease_avail_update(lease)
                        lst.remove(lease)
                if not lst:
                    del self._leases[key]

    def _try_launch_locked(self, spec: TaskSpec, blocked: list):
        """Attempt to launch ONE ready spec (caller holds _lock; the spec
        is NOT in self._ready from this method's point of view — callers
        pop/skip-queue on non-None). Returns:

        * ``(spec, worker)`` — launched; caller runs the launch tail
          outside the lock (worker None = async remote send).
        * ``"error"`` — failed fast (error stored); drop it.
        * ``None`` — not launchable now; leave/put it in the queue.

        Capacity-blocked class keys append to ``blocked`` (lease-fairness
        signal)."""
        class_key = self._lease_class(spec)
        pg_id, bundle = self._pg_key(spec)
        if not self.scheduler.is_feasible(
                spec.resources, pg_id, bundle,
                spec.scheduling_strategy):
            # Hard node-affinity to a dead/unknown node can never
            # succeed: fail fast (reference behavior). Anything
            # else stays queued as autoscaler demand — the
            # reference warns and waits for the cluster to grow.
            from ray_tpu.util.scheduling_strategies import (
                NodeAffinitySchedulingStrategy)
            strategy = spec.scheduling_strategy
            if pg_id is not None:
                # PG-targeted infeasibility can never be fixed by
                # cluster growth: either the PG was removed, or
                # the bundle's fixed capacity is exceeded.
                if self.scheduler.placement_group_exists(pg_id):
                    msg = (f"Task {spec.name} requires "
                           f"{spec.resources} which exceeds the "
                           "capacity of its placement group "
                           "bundle.")
                else:
                    msg = (f"Task {spec.name} was scheduled into "
                           "a placement group that does not "
                           "exist (removed or never created).")
                self._store_error(spec, ValueError(msg))
                return "error"
            if isinstance(strategy,
                          NodeAffinitySchedulingStrategy) and \
                    not strategy.soft:
                self._store_error(spec, ValueError(
                    f"Task {spec.name} has hard node affinity to "
                    f"node {strategy.node_id}, which is not alive "
                    "or lacks the required resources."))
                return "error"
            if spec.task_id not in self._infeasible_warned:
                self._infeasible_warned.add(spec.task_id)
                logger.warning(
                    "Task %s requires %s which no alive node "
                    "satisfies (cluster total: %s). It will stay "
                    "pending until the cluster grows (autoscaler "
                    "demand).", spec.name, spec.resources,
                    self.scheduler.total)
            return None
        # Locality-aware placement: with no explicit strategy, prefer
        # (softly) the node already holding the largest share of this
        # task's argument bytes — the args become local table reads
        # instead of cross-node pulls. An overloaded preferred node
        # spills the task back to the hybrid order.
        launch_strategy = spec.scheduling_strategy
        locality_node = None
        if pg_id is None and launch_strategy is None:
            locality_node = self._locality_preference(spec)
            if locality_node is not None:
                state = self.scheduler.node(locality_node)
                if state is None or not state.alive:
                    self._count_locality("remote")
                    locality_node = None
                elif state.utilization() >= self._cfg_locality_spillback:
                    self._count_locality("spillback")
                    locality_node = None
                else:
                    from ray_tpu.util.scheduling_strategies import (
                        NodeAffinitySchedulingStrategy)
                    launch_strategy = NodeAffinitySchedulingStrategy(
                        node_id=locality_node.hex(), soft=True)
        acquired = self.scheduler.try_acquire(
            spec.resources, pg_id, bundle,
            strategy=launch_strategy)
        if locality_node is not None and acquired is not None:
            self._count_locality(
                "local" if acquired[0] == locality_node
                else "spillback")
        if acquired is None:
            # No idle capacity: fall back to pipelining onto a live lease
            # of this class (reference: pipelining SUPPLEMENTS additional
            # lease requests, it never replaces them — idle CPUs always
            # win over queueing behind a busy worker).
            if class_key is not None:
                lease = self._find_lease(class_key)
                if lease is not None:
                    if locality_node is not None:
                        self._count_locality(
                            "local" if lease.node_id == locality_node
                            else "spillback")
                    self._inflight[spec.task_id] = spec
                    spec._node_id = lease.node_id
                    spec._acquired_bundle = lease.bidx
                    spec._lease = lease  # type: ignore[attr-defined]
                    spec._tpu_ids = lease.tpu_ids
                    lease.inflight += 1
                    self._lease_avail_update(lease)
                    spec.invalidated = False
                    spec._finalized = False
                    self.lease_stats["attached"] += 1
                    return (spec, None)
            blocked.append(class_key)
            return None
        node_id, bidx = acquired
        # Normal tasks on a remote daemon take the ASYNC path:
        # no head worker thread is parked for them (reference:
        # callback-driven direct task transport) — head thread
        # count stays flat as the cluster widens.
        conn = self._remote_nodes.get(node_id)
        if conn is not None and spec.kind == TaskKind.NORMAL:
            worker = None
        else:
            worker = self._pop_worker()
            if worker is None:
                self.scheduler.release(spec.resources, node_id,
                                       pg_id, bidx)
                return None
        self._inflight[spec.task_id] = spec
        spec._node_id = node_id  # type: ignore[attr-defined]
        spec._acquired_bundle = bidx  # type: ignore[attr-defined]
        spec.invalidated = False
        # App-level retries redispatch the same spec: re-arm the
        # exactly-once finalize claim for the new attempt.
        spec._finalized = False  # type: ignore[attr-defined]
        n_tpus = int(spec.resources.get("TPU", 0))
        if n_tpus >= 1:
            spec._tpu_ids = (  # type: ignore[attr-defined]
                self.scheduler.take_tpu_ids(node_id, n_tpus))
        spec._lease = None  # type: ignore[attr-defined]
        if worker is None and class_key is not None:
            # First task of its class on this node: open a
            # lease — followers pipeline onto it above.
            self._lease_counter += 1
            lease = _WorkerLease(
                f"ls-{self._lease_counter}", class_key,
                node_id, dict(spec.resources or {}), pg_id,
                bidx, getattr(spec, "_tpu_ids", None))
            self._leases.setdefault(class_key,
                                    []).append(lease)
            self._lease_avail_update(lease)
            spec._lease = lease  # type: ignore[attr-defined]
            self.lease_stats["created"] += 1
        return (spec, worker)

    def _locality_preference(self, spec: TaskSpec) -> Optional[NodeID]:
        """The node holding the largest share of the task's ObjectRef
        argument bytes (primary holders + broadcast/pull replicas), or
        None when no argument lives on a daemon. Caller holds _lock."""
        per_node: Dict[NodeID, int] = {}
        for a in list(spec.args) + list(spec.kwargs.values()):
            if not isinstance(a, ObjectRef):
                continue
            oid = a.object_id()
            rv = self._remote_values.get(oid)
            if rv is None:
                continue
            size = self.store.size_of(oid)
            if size <= 0:
                continue
            per_node[rv[0]] = per_node.get(rv[0], 0) + size
            for nid in (self._object_replicas.get(oid) or ()):
                if nid != rv[0]:
                    per_node[nid] = per_node.get(nid, 0) + size
        if not per_node:
            return None
        return max(per_node.items(), key=lambda kv: kv[1])[0]

    @staticmethod
    def _count_locality(outcome: str) -> None:
        try:
            builtin_metrics.lease_locality().inc(
                tags={"outcome": outcome})
        except Exception:  # noqa: BLE001 - accounting only
            pass

    def _launch(self, spec: TaskSpec, worker) -> None:
        """Launch tail (outside the lock) for a _try_launch_locked hit."""
        import time as _time
        spec._start_time = _time.monotonic()  # type: ignore[attr-defined]
        ctx = getattr(spec, "trace_ctx", None)
        if ctx is not None:
            self._record_trace_sched_spans(spec, ctx)
        self._record_event(spec, "RUNNING")
        if worker is None:
            self._submit_remote_async(spec)
        elif spec.kind == TaskKind.ACTOR_CREATION:
            worker.submit(lambda s=spec, w=worker: self._run_actor_creation(s, w))
        else:
            worker.submit(lambda s=spec, w=worker: self._run_normal_task(s, w))

    def _record_trace_sched_spans(self, spec: TaskSpec, ctx: dict) -> None:
        """Retroactive scheduler spans for a traced task at launch:
        ``sched::queue_wait`` covering submit -> launch (monotonic
        duration anchored at the submit span's wall time) and a
        zero-length ``sched::lease_grant`` marker carrying the lease
        identity (the grant itself is an instant in this scheduler — the
        waiting shows up in queue_wait)."""
        mono0 = getattr(spec, "_trace_submit_mono", None)
        if mono0 is None:
            return
        from ray_tpu.util import tracing
        wait = spec._start_time - mono0
        wall0 = getattr(spec, "_trace_submit_wall", 0.0)
        tracing.record_complete_span(
            "sched::queue_wait", ctx, wall_start=wall0, duration=wait,
            attributes={"stage": "queue", "task": spec.name})
        lease = getattr(spec, "_lease", None)
        if lease is not None:
            tracing.record_complete_span(
                "sched::lease_grant", ctx, wall_start=wall0 + wait,
                duration=0.0,
                attributes={"stage": "lease", "task": spec.name,
                            "lease_id": lease.lease_id})

    def _queue_ready_locked(self, spec: TaskSpec) -> None:
        ck = self._lease_class(spec)
        if ck is None:
            self._ready.append(spec)
        else:
            dq = self._ready_by_class.get(ck)
            if dq is None:
                dq = self._ready_by_class[ck] = self._deque()
            dq.append(spec)

    def _ready_specs_locked(self):
        """All queued-ready specs, class buckets first (caller holds
        _lock; iteration order is the dispatch probe order)."""
        for dq in self._ready_by_class.values():
            yield from dq
        yield from self._ready

    def _dispatch_single(self, spec: TaskSpec) -> None:
        """O(1) dispatch for one just-ready task — the submit hot path:
        try a lease attach or a direct acquisition for THIS spec only and
        queue it otherwise. Full _dispatch() scans remain the capacity-
        freed path (completions, node joins)."""
        self._chaos_delay("testing_dispatch_delay_us")
        with self._lock:
            if self._shutdown:
                return
            ck = self._lease_class(spec)
            if ck is not None and self._ready_by_class.get(ck):
                # FIFO within a class: earlier same-class submits go first.
                self._ready_by_class[ck].append(spec)
                return
            res = self._try_launch_locked(spec, [])
            if res is None:
                self._queue_ready_locked(spec)
                return
            if res == "error":
                return
        self._launch(*res)

    def _dispatch(self) -> None:
        self._chaos_delay("testing_dispatch_delay_us")
        while True:
            launched = None
            with self._lock:
                if self._shutdown:
                    return
                blocked: list = []
                # Class buckets: probe ONE representative per class —
                # same-class tasks are interchangeable, so its verdict
                # (launch / error / blocked) covers the whole bucket.
                for ck, dq in self._ready_by_class.items():
                    if not dq:
                        continue
                    res = self._try_launch_locked(dq[0], blocked)
                    if res is None:
                        continue
                    dq.popleft()
                    if not dq:
                        del self._ready_by_class[ck]
                    launched = True if res == "error" else res
                    break
                if launched is None:
                    # Unleasable tasks: FIFO scan (original semantics).
                    for i, spec in enumerate(self._ready):
                        res = self._try_launch_locked(spec, blocked)
                        if res is None:
                            continue
                        self._ready.pop(i)
                        launched = True if res == "error" else res
                        break
                if launched is None:
                    # Full scan completed: remember which classes were
                    # capacity-blocked (lease fairness: a draining lease
                    # releases early iff a DIFFERENT class is starved).
                    self._lease_contended = set(blocked)
            if launched is None or launched is True:
                if launched is None:
                    return
                continue
            self._launch(*launched)

    def _pop_worker(self) -> Optional[Executor]:
        if self._idle_workers:
            return self._idle_workers.pop()
        if len(self._all_workers) >= self._max_workers:
            return None
        wid = WorkerID.from_random()
        worker = SerialThreadExecutor(wid, name=f"ray_tpu-worker-{wid.hex()[:8]}")
        self._all_workers.append(worker)
        return worker

    def _return_worker(self, worker: Optional[Executor]) -> None:
        if worker is None:
            return  # async remote task: no head thread was consumed
        with self._lock:
            if not worker.dead and worker.actor_id is None:
                self._idle_workers.append(worker)

    # ------------------------------------------------------------------
    # Execution (thread backend: runs in executor threads)
    # ------------------------------------------------------------------

    def _resolve_args(self, spec: TaskSpec, conn=None,
                      to_process: bool = False):
        """Materialize ObjectRef args. With a target daemon connection,
        arguments whose payload lives in a node object table travel as
        tiny markers: payload on THAT daemon → local read; payload on a
        PEER daemon → the executing daemon pulls it directly from the
        peer's object server (zero bytes through the head — reference:
        object_manager.h node-to-node chunked pulls). For a local worker
        PROCESS target, arena-resident arrays travel as ArenaArrayRef
        markers the worker resolves to zero-copy shm views (plasma's
        cross-process mission: no copy between store and worker)."""
        from ray_tpu._private.dataplane import ObjectMarker

        def resolve(a):
            if not isinstance(a, ObjectRef):
                return a
            oid = a.object_id()
            if conn is not None:
                with self._lock:
                    rv = self._remote_values.get(oid)
                    owner_conn = (self._remote_nodes.get(rv[0])
                                  if rv is not None else None)
                    alt_addrs = ()
                    spill_uri = None
                    if rv is not None:
                        # Every OTHER live holder rides the marker as a
                        # failover candidate, and a durable spill URI as
                        # the last data-plane resort — a mid-pull holder
                        # death resumes instead of erroring into
                        # reconstruction.
                        reps = self._object_replicas.get(oid)
                        if reps:
                            alt_addrs = tuple(
                                c.object_addr
                                for nid in reps
                                if nid != rv[0] and nid != conn.node_id
                                and (c := self._remote_nodes.get(nid))
                                is not None and c.object_addr is not None)
                        rec = self._spill_uris_by_key.get(rv[1])
                        if rec is not None:
                            spill_uri = rec[0]
                # Broadcasted head-resident objects stay materialized at
                # the head AND ship as markers: the consumer daemon's
                # local table (tree push already landed a replica) or a
                # nearby holder serves the bytes, never the head again.
                if rv is not None and \
                        (oid in self._broadcasted or
                         not self.store.is_materialized(oid)):
                    if rv[0] == conn.node_id:
                        return ObjectMarker(rv[1])
                    if owner_conn is not None and \
                            owner_conn.object_addr is not None:
                        # The executing daemon will pull a copy: note the
                        # (oid, key) so task completion can register it
                        # as an in-memory replica holder.
                        self._note_pull_demand(oid, conn.node_id)
                        pulls = getattr(spec, "_marker_pulls", None)
                        if pulls is None:
                            pulls = spec._marker_pulls = []
                        pulls.append((oid, rv[1]))
                        return ObjectMarker(rv[1],
                                            owner_addr=owner_conn.object_addr,
                                            alt_addrs=alt_addrs,
                                            spill_uri=spill_uri)
            if conn is not None and \
                    self.store.size_of(oid) >= self._cfg_inline_limit:
                # Head-resident payload about to ship inline to a
                # daemon: head egress. Enough distinct consumer nodes
                # flips the object to a broadcast tree.
                self._note_pull_demand(oid, conn.node_id)
            if to_process and self.store.native_array_key(oid) is not None:
                from ray_tpu._private.worker_process import ArenaArrayRef
                # The task's dependency pin keeps the entry alive until
                # the task finishes, so the worker's read cannot race a
                # free.
                return ArenaArrayRef(oid.hex())
            return self.store.get(oid)

        args = [resolve(a) for a in spec.args]
        kwargs = {k: resolve(v) for k, v in spec.kwargs.items()}
        return args, kwargs

    #: distinct consumer nodes before an object auto-upgrades from
    #: point-to-point pulls to one spanning-tree broadcast.
    _AUTO_BROADCAST_MIN_CONSUMERS = 4

    def _note_pull_demand(self, oid: ObjectID, node_id: NodeID) -> None:
        """Auto-broadcast trigger: the same object heading to its Nth
        distinct node is a fan-out workload — O(N) transfers out of one
        source become one bounded-fanout tree (O(log N) depth, source
        egress capped at fanout x size)."""
        with self._lock:
            nodes = self._pull_demand.setdefault(oid, {})
            nodes[node_id] = None
            if len(nodes) < self._AUTO_BROADCAST_MIN_CONSUMERS or \
                    oid in self._broadcasted or \
                    oid in self._broadcast_inflight:
                return
            self._broadcast_inflight[oid] = None
        threading.Thread(target=self._broadcast_bg, args=(oid,),
                         daemon=True, name="auto-broadcast").start()

    def _broadcast_bg(self, oid: ObjectID) -> None:
        try:
            self._broadcast_object(oid)
        except Exception:  # noqa: BLE001 - broadcast is an optimization
            logger.exception("auto-broadcast of %s failed; consumers "
                             "fall back to point-to-point pulls",
                             oid.hex()[:12])
        finally:
            with self._lock:
                self._broadcast_inflight.pop(oid, None)

    def _store_results(self, spec: TaskSpec, result: Any) -> None:
        ctx = getattr(spec, "trace_ctx", None)
        if ctx is None:
            return self._store_results_inner(spec, result)
        import time as _time
        from ray_tpu.util import tracing
        wall = _time.time()
        mono0 = _time.monotonic()
        try:
            return self._store_results_inner(spec, result)
        finally:
            tracing.record_complete_span(
                "task::store_result", ctx, wall_start=wall,
                duration=_time.monotonic() - mono0,
                attributes={"stage": "store", "task": spec.name})

    def _store_results_inner(self, spec: TaskSpec, result: Any) -> None:
        if getattr(spec, "invalidated", False):
            # The task's node died while it ran; a retry owns the return
            # objects now (reference: a worker on a dead node can't deliver).
            return
        self._release_task_deps(spec)
        node_id = getattr(spec, "_node_id", None)
        if node_id is not None:
            with self._lock:
                # Same bound as _lineage: past it, objects are simply not
                # reconstructable (the maps must not grow without limit in
                # long-running drivers).
                # Remote-daemon results return inline and live in the
                # HEAD's store — recording the daemon as their location
                # would make its death discard values we still hold.
                if node_id not in self._remote_nodes and \
                        len(self._object_locations) < \
                        self._cfg_obj_loc_max:
                    for oid in spec.return_ids:
                        self._object_locations[oid] = node_id
                # Marker args the daemon pulled are now in-memory
                # REPLICAS there (the data plane caches pulls): register
                # the extra holder so node death can re-point the fetch
                # instead of re-executing (bounded like the location
                # table; replicas are an optimization, never required).
                pulls = getattr(spec, "_marker_pulls", None)
                if pulls and node_id in self._remote_nodes:
                    for oid, _key in pulls:
                        if oid in self._remote_values and \
                                self._remote_values[oid][0] != node_id \
                                and len(self._object_replicas) < \
                                self._cfg_obj_loc_max:
                            self._object_replicas.setdefault(
                                oid, {})[node_id] = None
                            # Throttled durable mirror (head failover
                            # accounting; holders are advisory after a
                            # head restart since node ids re-mint).
                            if self.gcs_store is not None:
                                try:
                                    self.gcs_store.record_object_replica(
                                        oid.hex(), node_id.hex())
                                except OSError:
                                    pass
        n = spec.num_returns
        if n == 0:
            return
        if n == "dynamic":
            # Dynamic generator returns (reference: _raylet.pyx:624): each
            # yielded value becomes its own object; the declared return object
            # holds the list of refs.
            if not self.refs.has(spec.return_ids[0]):
                return  # every handle dropped while the task ran
            item_refs = []
            for i, item in enumerate(result):
                oid = ObjectID.for_return(spec.task_id, i + 2)
                self.store.put_inline(oid, item)
                self.refs.add_owned(oid)
                item_refs.append(ObjectRef(oid))
            self._store_if_referenced(spec.return_ids[0], item_refs)
            return
        if n == 1:
            from ray_tpu._private.multinode import RemoteValueStub
            if isinstance(result, RemoteValueStub):
                self._store_remote_result(spec, spec.return_ids[0], result)
            else:
                self._store_if_referenced(spec.return_ids[0], result)
            return
        if not isinstance(result, (tuple, list)) or len(result) != n:
            from ray_tpu._private.multinode import (MismatchedReturn,
                                                    RemoteValueStub,
                                                    describe_value)
            if isinstance(result, MismatchedReturn):
                # Daemon detected the shape mismatch and described the
                # real value instead of storing it (nothing to free).
                desc = result.desc
            elif isinstance(result, RemoteValueStub):
                # Defensive: an oversized mismatched single-return stub.
                # Describe by size (never ship the payload to the head
                # just for an error string) and free the daemon copy —
                # it must not sit in the node's table until session end.
                desc = (f"a single daemon-resident value "
                        f"({result.size} bytes)")
                try:
                    result.conn.free_object(result.key)
                except Exception:  # noqa: BLE001 - best effort
                    pass
            else:
                desc = describe_value(result)
            self._store_error(spec, ValueError(
                f"Task {spec.name} declared num_returns={n} but returned "
                f"{desc}"))
            return
        from ray_tpu._private.multinode import RemoteValueStub
        for oid, value in zip(spec.return_ids, result):
            if isinstance(value, RemoteValueStub):
                # Multi-return daemon task: big elements stay daemon-
                # resident individually (shuffle partials ride the data
                # plane, never the head).
                self._store_remote_result(spec, oid, value)
            else:
                self._store_if_referenced(oid, value)

    def _store_remote_result(self, spec: TaskSpec, oid: ObjectID,
                             stub) -> None:
        """Seal a daemon-resident result as a lazily-fetched store entry
        (mirrors _store_if_referenced's dropped-handle handling: if nobody
        can ever read it, free the daemon-side payload instead)."""
        def drop():
            try:
                stub.conn.free_object(stub.key)
            except Exception:  # noqa: BLE001 - best effort
                pass

        if not self.refs.has(oid):
            drop()
            return
        with self._lock:
            # Atomic with remove_node's dooming (same lock): either the
            # node death already invalidated this spec (the retry owns the
            # object — never seal a fetch against a dead connection), or
            # the seal lands first and node-death recovery reconstructs
            # the daemon-resident value.
            if getattr(spec, "invalidated", False):
                return
            self._remote_values[oid] = (stub.conn.node_id, stub.key)
            self._remote_keys[stub.key] = oid
            self.store.put_remote(oid, stub.fetch, stub.size)
        if not self.refs.has(oid):
            with self._lock:
                self._remote_values.pop(oid, None)
                self._remote_keys.pop(stub.key, None)
            self.store.free([oid])
            drop()

    def _store_if_referenced(self, oid: ObjectID, value: Any,
                             is_exception: bool = False) -> None:
        """Store a task result unless every handle was already dropped.

        The recheck AFTER the store closes the race with a handle dying
        between the check and the seal: either the death happened before the
        recheck (we free inline) or after it (the counter still tracked the
        object, so remove_local returns it and the GC thread frees it)."""
        if not self.refs.has(oid):
            return
        self.store.put_inline(oid, value, is_exception=is_exception)
        if not self.refs.has(oid):
            self.store.free([oid])

    def _store_error(self, spec: TaskSpec, exc: BaseException) -> None:
        self._release_task_deps(spec)
        if not isinstance(exc, (TaskError, ActorDiedError, TaskCancelledError,
                                GetTimeoutError, NodeDiedError,
                                ObjectLostError)):
            exc = TaskError.from_exception(exc, spec.name)
        for oid in spec.return_ids:
            self._store_if_referenced(oid, exc, is_exception=True)
        self._record_event(spec, "FAILED")

    def _should_retry(self, spec: TaskSpec, exc: BaseException) -> bool:
        if spec.attempt_number >= spec.max_retries:
            return False
        retry_on = spec.retry_exceptions
        if isinstance(exc, TaskError):
            # Application error: retry only if retry_exceptions allows.
            if retry_on is True:
                return True
            if isinstance(retry_on, (list, tuple)):
                return isinstance(exc.cause, tuple(retry_on))
            return False
        # System error (worker died): always retriable within budget.
        return True

    def _run_normal_task(self, spec: TaskSpec, worker: Executor) -> None:
        try:
            fn = self.functions.load(spec.function_id)
            args, kwargs = self._resolve_args(
                spec, self._remote_conn(spec),
                to_process=self._use_process_worker(spec))
            _task_context.spec = spec
            try:
                from ray_tpu.util import tracing
                with tracing.continue_context(
                        getattr(spec, "trace_ctx", None),
                        f"task::{spec.name}", {"stage": "execute"}):
                    # Remote tasks apply runtime_env daemon-side (the
                    # request carries it) and process-worker tasks apply
                    # it worker-side (where a pip venv is active); only
                    # thread-local runs apply it here.
                    if spec.runtime_env and self._remote_conn(spec) is None \
                            and not self._use_process_worker(spec):
                        from ray_tpu._private import runtime_env as _renv
                        _renv.setup(spec.runtime_env)
                        with _renv.applied(spec.runtime_env):
                            result = self._invoke_user(spec, fn, args,
                                                       kwargs)
                    else:
                        result = self._invoke_user(spec, fn, args, kwargs)
            finally:
                _task_context.spec = None
            self._store_results(spec, result)
            self._record_event(spec, "FINISHED")
        except BaseException as e:  # noqa: BLE001
            if getattr(spec, "invalidated", False):
                self._return_worker(worker)
                self._dispatch()
                return
            if isinstance(e, TaskCancelledError):
                # Force-cancel killed the worker process: terminal, never
                # retried (reference: cancelled tasks are not retried).
                self._store_error(spec, e)
                self._finish_task(spec, worker)
                return
            err = e if isinstance(e, TaskError) else TaskError(
                e, traceback.format_exc(), spec.name)
            # A dropped node connection is a SYSTEM failure (node death),
            # not an application error — probe retry with the raw
            # exception so the always-retriable path applies even when the
            # death handler hasn't invalidated this spec yet. Likewise a
            # failed node-to-node object pull (the arg's owner died): the
            # retry waits on reconstruction, not the user's code. A died
            # worker PROCESS (crash/kill) is the reference's
            # WorkerCrashedError — system-retriable too.
            from ray_tpu._private.dataplane import ObjectPullError
            from ray_tpu._private.multinode import RemoteNodeDiedError
            from ray_tpu._private.worker_process import WorkerCrashedError
            probe = e if isinstance(e, (RemoteNodeDiedError,
                                        WorkerCrashedError)) else err
            if isinstance(err, TaskError) and \
                    isinstance(err.cause, (ObjectPullError,
                                           WorkerCrashedError)):
                probe = err.cause
            if self._should_retry(spec, probe):
                spec.attempt_number += 1
                self._finish_task(spec, worker, retried=True)
                logger.warning("Retrying task %s (attempt %d/%d)", spec.name,
                               spec.attempt_number, spec.max_retries)
                self._resolve_dependencies(spec)
                return
            self._store_error(spec, err)
        self._finish_task(spec, worker)

    def _submit_remote_async(self, spec: TaskSpec) -> None:
        """Ship a normal task to its remote daemon without parking a head
        thread: the send runs on the completion pool, the reply arrives as
        a callback (reference: direct_task_transport.cc — client-side
        submission is fully callback-driven)."""
        conn = self._remote_conn(spec)

        def send():
            if getattr(spec, "invalidated", False):
                self._dispatch()  # node died between dispatch and send
                return
            try:
                if conn is None:
                    from ray_tpu._private.multinode import \
                        RemoteNodeDiedError
                    raise RemoteNodeDiedError(
                        "task's node vanished before the send")
                args, kwargs = self._resolve_args(spec, conn)
                lease = getattr(spec, "_lease", None)
                conn.execute_task_async(
                    spec, self.functions, args, kwargs,
                    self._result_store_limit(spec),
                    lambda reply: self._complete_remote_task(spec, conn,
                                                             reply),
                    lease_id=lease.lease_id if lease is not None else None,
                    class_id=(self._class_wire_id(lease.class_key)
                              if lease is not None else None))
            except BaseException as e:  # noqa: BLE001
                self._remote_task_error(spec, e)

        # Inline send: the frame write is microseconds (args were already
        # resolved to values/markers when the task became ready), and a
        # pool hop per task costs more than it hides at 5k+ tasks/s. The
        # REPLY is still callback-driven — no head thread parks while the
        # daemon works.
        send()

    def _complete_remote_task(self, spec: TaskSpec, conn, reply: dict
                              ) -> None:
        """Continuation for an async remote task (runs on the completion
        pool): unpack, store, finish — mirroring _run_normal_task's
        terminal handling without a dedicated thread."""
        if reply.get("reclaimed"):
            # Spillback: the daemon handed this queued-not-started task
            # back (capacity freed elsewhere). Release its lease ride
            # and re-dispatch — same accounting as a retry, without
            # consuming a retry attempt.
            if getattr(spec, "invalidated", False):
                self._dispatch()
                return
            with self._lock:
                self.lease_stats["reclaimed"] += 1
            self._finish_task(spec, None, retried=True)
            self._resolve_dependencies(spec)
            return
        try:
            if reply.get("type") == "died":
                from ray_tpu._private.multinode import RemoteNodeDiedError
                raise RemoteNodeDiedError(
                    f"node {conn.address} died (or chaos fired) while the "
                    "task was in flight")
            result = conn._unpack(reply, spec.name)
            self._store_results(spec, result)
            self._record_event(spec, "FINISHED")
        except BaseException as e:  # noqa: BLE001
            self._remote_task_error(spec, e)
            return
        self._finish_task(spec, None)

    def _remote_task_error(self, spec: TaskSpec, e: BaseException) -> None:
        """Shared error/retry terminal for the async remote path. By the
        time a 'died' completion is delivered, the connection's close()
        has already run the node-death bookkeeping (on_death fires before
        callbacks), so spec.invalidated is authoritative here — no wait
        loop needed."""
        if getattr(spec, "invalidated", False):
            self._dispatch()
            return
        err = e if isinstance(e, TaskError) else TaskError(
            e, traceback.format_exc(), spec.name)
        from ray_tpu._private.dataplane import ObjectPullError
        from ray_tpu._private.multinode import RemoteNodeDiedError
        from ray_tpu._private.worker_process import WorkerCrashedError
        probe = e if isinstance(e, RemoteNodeDiedError) else err
        if isinstance(err, TaskError) and \
                isinstance(err.cause, (ObjectPullError, WorkerCrashedError)):
            probe = err.cause
        if self._should_retry(spec, probe):
            spec.attempt_number += 1
            self._finish_task(spec, None, retried=True)
            logger.warning("Retrying task %s (attempt %d/%d)", spec.name,
                           spec.attempt_number, spec.max_retries)
            self._resolve_dependencies(spec)
            return
        self._store_error(spec, err)
        self._finish_task(spec, None)

    def _running_normal_tasks(self) -> List[TaskSpec]:
        with self._lock:
            return [s for s in self._inflight.values()
                    if s.kind == TaskKind.NORMAL]

    def _oom_kill_task(self, spec: TaskSpec) -> None:
        """Memory-monitor victim: discard the task's (still running) work
        like a node-death zombie, release its resources, and retry within
        budget or seal OutOfMemoryError (reference: raylet worker killing
        + task OOM retry)."""
        from ray_tpu.exceptions import OutOfMemoryError
        with self._lock:
            if spec.task_id not in self._inflight:
                return
        if spec.return_ids and all(
                self.store.contains(oid) for oid in spec.return_ids):
            return  # effectively completed; nothing to reclaim by killing
        if not self._try_claim_finalize(spec):
            return  # the worker finalized first
        with self._lock:  # atomic vs. _store_remote_result's seal
            spec.invalidated = True
            handle = self._proc_tasks.get(spec.task_id)
            if handle is not None:
                # Process-backed victim: a REAL kill — the worker's RSS
                # goes back to the OS (reference: raylet worker killing
                # actually reclaims memory; threads can only discard).
                # Under the lock: the release path pops _proc_tasks under
                # this lock, so the kill can't hit a re-leased worker.
                handle.kill(wait=False)
        self._release_task_resources(spec)
        if spec.attempt_number < spec.max_retries:
            retry = spec.clone_for_retry()
            with self._lock:
                for oid in retry.return_ids:
                    if oid in self._lineage:
                        self._lineage[oid] = retry
            self._register_task_refs(retry)
            self._release_task_deps(spec)
            self._record_event(spec, "OOM_RETRY")
            self._resolve_dependencies(retry)
        else:
            err = OutOfMemoryError(
                f"Task {spec.name} was killed by the memory monitor: node "
                "memory usage exceeded the configured threshold "
                "(memory_usage_threshold) and its retry budget is spent.")
            self._release_task_deps(spec)
            for oid in spec.return_ids:
                self._store_if_referenced(oid, err, is_exception=True)
            self._record_event(spec, "FAILED")
        self._dispatch()

    def _try_claim_finalize(self, spec: TaskSpec) -> bool:
        """Exactly-once claim on a task's resource release: the finishing
        worker and an asynchronous killer (OOM monitor, node death) race to
        finalize; only the winner releases resources."""
        with self._lock:
            if getattr(spec, "_finalized", False):
                return False
            spec._finalized = True  # type: ignore[attr-defined]
            self._inflight.pop(spec.task_id, None)
            return True

    def _release_task_resources(self, spec: TaskSpec) -> None:
        lease = getattr(spec, "_lease", None)
        if lease is not None:
            # The LEASE owns the acquisition; this task only rode it.
            spec._lease = None  # type: ignore[attr-defined]
            with self._lock:
                blocked = getattr(spec, "_blocked_release", False)
                spec._blocked_release = False  # type: ignore[attr-defined]
            if blocked:
                gate = self._unblock_lease_gated(lease)
                if not lease.dropped:
                    # Finalized while blocked in a nested get (lease
                    # capacity was lent out): re-take it so the lease's
                    # eventual drop releases exactly once.
                    self.scheduler.force_acquire(
                        lease.resources, lease.node_id,
                        lease.pg_id, lease.bidx)
                if gate:
                    self._send_unspill_and_open(lease)
            self._lease_task_done(spec, lease)
            return
        with self._lock:
            # A blocked client get (client_get_release) already gave the
            # resources back; consuming the flag here makes release
            # exactly-once when the task finalizes mid-block.
            blocked = getattr(spec, "_blocked_release", False)
            spec._blocked_release = False  # type: ignore[attr-defined]
        pg_id, _ = self._pg_key(spec)
        node_id = getattr(spec, "_node_id", None)
        bidx = getattr(spec, "_acquired_bundle", -1)
        if not blocked:
            self.scheduler.release(spec.resources, node_id, pg_id, bidx)
        tpu_ids = getattr(spec, "_tpu_ids", None)
        if tpu_ids and node_id is not None:
            self.scheduler.return_tpu_ids(node_id, tpu_ids)
            spec._tpu_ids = None  # type: ignore[attr-defined]

    def _unblock_lease_gated(self, lease) -> bool:
        """One task's blocked get returned: decrement the blocked count.
        The LAST unblocker must hold the gate (blocked stays >=1, so no
        _dispatch can attach) until the unspill frame is ON THE WIRE —
        decrement-then-send would let an attach frame overtake the
        unspill and execute on a still-spilled daemon executor. Returns
        True iff the caller owns the gate and must follow with
        _send_unspill_and_open."""
        with self._lock:
            lease.blocked -= 1
            if lease.blocked == 0:
                lease.blocked = 1  # gate: attaches stay closed
                return True
            self._lease_avail_update(lease)
        return False

    def _send_unspill_and_open(self, lease) -> None:
        """Second half of the gated unblock: ship the unspill frame,
        then open attaches (arithmetic decrement — a NEW blocked get
        during the send may have incremented, and its spill frame
        travels after ours, which the daemon applies in order)."""
        if not lease.dropped:
            conn = self._remote_nodes.get(lease.node_id)
            if conn is not None:
                conn.unspill_lease(lease.lease_id)
        with self._lock:
            lease.blocked -= 1
            self._lease_avail_update(lease)
        self._dispatch()

    def client_get_release(self, task_id_hex: str) -> Optional[TaskSpec]:
        """A client runtime's get blocked inside this running task:
        release the task's resources so nested/dependent work can run
        (the client-side analog of Runtime.get's own blocked-worker
        release; reference: NotifyDirectCallTaskBlocked). Returns the
        spec iff released — pass it to client_get_reacquire after."""
        try:
            task_id = TaskID(bytes.fromhex(task_id_hex))
        except (ValueError, TypeError):
            return None
        with self._lock:
            spec = self._inflight.get(task_id)
            if spec is None or spec.kind != TaskKind.NORMAL or \
                    not spec.resources:
                return None
            if getattr(spec, "_finalized", False) or \
                    getattr(spec, "_blocked_release", False):
                return None
            lease = getattr(spec, "_lease", None)
            if lease is not None and lease.dropped:
                return None
            spec._blocked_release = True  # type: ignore[attr-defined]
            if lease is not None:
                # INSIDE the lock: _find_lease/_lease_task_done read
                # blocked under it — set-after-release would let a
                # dispatch attach a same-class child to this lease in
                # the window, landing it behind its blocked parent.
                lease.blocked += 1
                self._lease_avail_update(lease)
        if lease is not None:
            # A leased task blocks its lease's serial executor, so lending
            # out the LEASE's acquisition is safe: nothing else can run on
            # it until this task's get unblocks (composition: nested work
            # must be schedulable while the parent waits). Tasks already
            # pipelined BEHIND the blocked one daemon-side could include
            # the very child being waited on — spill them to free threads
            # and stop attaching until the get returns.
            self.scheduler.release(lease.resources, lease.node_id,
                                   lease.pg_id, lease.bidx)
            conn = self._remote_nodes.get(lease.node_id)
            if conn is not None:
                conn.spill_lease(lease.lease_id)
        else:
            pg_id, _ = self._pg_key(spec)
            self.scheduler.release(spec.resources,
                                   getattr(spec, "_node_id", None), pg_id,
                                   getattr(spec, "_acquired_bundle", -1))
        self._dispatch()
        return spec

    def client_get_reacquire(self, spec: TaskSpec) -> None:
        """Re-take the blocked task's resources once its get unblocked.
        If the task finalized meanwhile, _release_task_resources consumed
        the flag (and skipped its release) — nothing to re-take."""
        with self._lock:
            if not getattr(spec, "_blocked_release", False):
                return
            spec._blocked_release = False  # type: ignore[attr-defined]
            lease = getattr(spec, "_lease", None)
        if lease is not None:
            gate = self._unblock_lease_gated(lease)
            if not lease.dropped:
                self.scheduler.force_acquire(lease.resources, lease.node_id,
                                             lease.pg_id, lease.bidx)
            if gate:
                self._send_unspill_and_open(lease)
            return
        pg_id, _ = self._pg_key(spec)
        self.scheduler.force_acquire(
            spec.resources, getattr(spec, "_node_id", None), pg_id,
            getattr(spec, "_acquired_bundle", -1))

    def _finish_task(self, spec: TaskSpec, worker: Executor,
                     retried: bool = False) -> None:
        if self._try_claim_finalize(spec) and not getattr(
                spec, "invalidated", False):
            # (invalidated + claimed: node death released the node's
            # resources wholesale — nothing to give back here.)
            self._release_task_resources(spec)
        self._return_worker(worker)
        self._dispatch()

    # ------------------------------------------------------------------
    # Actors
    # ------------------------------------------------------------------

    def create_actor(self, spec: TaskSpec, *, max_restarts: int,
                     max_concurrency: int, name: str = "",
                     namespace: str = "default",
                     get_if_exists: bool = False,
                     concurrency_groups: Optional[Dict[str, int]] = None,
                     lifetime: Optional[str] = None) -> ActorID:
        actor_id = spec.actor_id
        if lifetime == "detached" and not name:
            # A detached actor is reachable ONLY through the named-actor
            # registry once its creator exits — an anonymous one would
            # be an unkillable orphan.
            raise ValueError(
                "detached actors must be created with a name "
                "(.options(name=..., lifetime='detached'))")
        state = ActorState(actor_id, spec, max_restarts, max_concurrency,
                           name, namespace,
                           concurrency_groups=concurrency_groups,
                           lifetime=lifetime)
        with self._lock:
            # Uniqueness check + registration atomically, so concurrent
            # creates with the same name cannot both succeed.
            if name:
                existing = self._named_actors.get((namespace, name))
                if existing is not None:
                    if get_if_exists:
                        return existing
                    raise ValueError(
                        f"Actor name {name!r} already taken in namespace "
                        f"{namespace!r}")
                self._named_actors[(namespace, name)] = actor_id
            self._actors[actor_id] = state
        if name and self.gcs_store is not None:
            # Persist OUTSIDE the runtime lock — the store fsyncs a file
            # per mutation; dispatch must not stall on disk I/O.
            try:
                cls_bytes = self.functions.get_bytes(spec.function_id)
            except KeyError:
                cls_bytes = None  # unpicklable: cannot survive restarts
            creation_payload = None
            if lifetime == "detached":
                # Detached actors must be restartable AFTER a head
                # restart — persist the __init__ args so the rebound
                # creation spec is re-runnable (best effort: unpicklable
                # args degrade to rebind-without-restart).
                try:
                    creation_payload = serialization.serialize(
                        (spec.args, spec.kwargs))
                except Exception:  # noqa: BLE001
                    creation_payload = None
            self.gcs_store.record_actor(
                actor_id.hex(), name, namespace, max_restarts,
                max_concurrency, cls_bytes=cls_bytes,
                resources=dict(spec.resources or {}),
                concurrency_groups=concurrency_groups,
                lifetime=lifetime,
                creation_payload=creation_payload)
        spec.return_ids = [ObjectID.for_return(spec.task_id, 1)]
        self._register_task_refs(spec)
        self._record_event(spec, "SUBMITTED")
        self._resolve_dependencies(spec)
        return actor_id

    def _make_actor_executor(self, state: ActorState) -> Executor:
        import asyncio
        wid = WorkerID.from_random()
        name = f"ray_tpu-actor-{state.name or state.actor_id.hex()[:8]}"
        cls = self.functions.load(state.creation_spec.function_id)
        is_async = any(
            asyncio.iscoroutinefunction(getattr(cls, m, None))
            for m in dir(cls) if not m.startswith("__"))
        if is_async:
            ex: Executor = AsyncioActorExecutor(
                wid, name, max(state.max_concurrency, 1000 if
                               state.max_concurrency <= 1 else
                               state.max_concurrency),
                groups=state.concurrency_groups)
        elif state.concurrency_groups:
            ex = ConcurrencyGroupExecutor(wid, name,
                                          state.concurrency_groups,
                                          state.max_concurrency)
        elif state.max_concurrency > 1:
            ex = ThreadPoolActorExecutor(wid, name, state.max_concurrency)
        else:
            ex = SerialThreadExecutor(wid, name)
        ex.actor_id = state.actor_id
        return ex

    def _release_actor_resources(self, state: ActorState) -> None:
        """Release the creation-time resources exactly once, and only if they
        were actually acquired (the spec carries _acquired_bundle iff the
        dispatcher acquired them)."""
        spec = state.creation_spec
        with state.lock:
            if state.resources_released:
                return
            if not hasattr(spec, "_acquired_bundle"):
                state.resources_released = True
                return
            state.resources_released = True
        pg_id, _ = self._pg_key(spec)
        node_id = getattr(spec, "_node_id", None)
        bidx = getattr(spec, "_acquired_bundle", -1)
        self.scheduler.release(spec.resources, node_id, pg_id, bidx)
        tpu_ids = getattr(spec, "_tpu_ids", None)
        if tpu_ids and node_id is not None:
            self.scheduler.return_tpu_ids(node_id, tpu_ids)
            spec._tpu_ids = None  # type: ignore[attr-defined]

    def _run_actor_creation(self, spec: TaskSpec, worker: Executor) -> None:
        state = self._actors[spec.actor_id]
        try:
            cls = self.functions.load(spec.function_id)
            args, kwargs = self._resolve_args(spec, self._remote_conn(spec))
            _task_context.spec = spec
            try:
                if spec.runtime_env and self._remote_conn(spec) is None \
                        and not self._use_process_worker(spec):
                    from ray_tpu._private import runtime_env as _renv
                    _renv.setup(spec.runtime_env)
                    with _renv.applied(spec.runtime_env):
                        instance = self._invoke_actor_init(spec, cls, args,
                                                           kwargs)
                else:
                    instance = self._invoke_actor_init(spec, cls, args,
                                                       kwargs)
            finally:
                _task_context.spec = None
            if spec.invalidated:
                # Node died mid-__init__; a cloned creation owns the actor
                # now. Discard this thread's work entirely.
                self._return_worker(worker)
                self._dispatch()
                return
            executor = self._make_actor_executor(state)
            killed = False
            with state.lock:
                if state.dead:
                    # Killed mid-construction.
                    executor.stop()
                    killed = True
                else:
                    state.instance = instance
                    state.executor = executor
                    state.created.set()
                    # Flush tasks that dep-resolved before creation finished,
                    # preserving their arrival order.
                    for queued in state.pre_creation_queue:
                        self._submit_to_actor_executor(executor, queued,
                                                       state)
                    state.pre_creation_queue.clear()
            if killed:
                self._store_error(spec, state.death_cause)
                self._release_actor_resources(state)
            else:
                self._release_task_deps(spec)
                self.store.put_inline(spec.return_ids[0], None)
                self._record_event(spec, "FINISHED")
        except BaseException as e:  # noqa: BLE001
            if spec.invalidated or self._node_death_invalidated(spec, e):
                self._return_worker(worker)
                self._dispatch()
                return
            err = TaskError(e, traceback.format_exc(),
                            f"{spec.name}.__init__")
            with state.lock:
                state.dead = True
                state.death_cause = err
                state.created.set()
                unfinished = list(state.unfinished.values())
                state.unfinished.clear()
                state.pre_creation_queue.clear()
            self._store_error(spec, err)
            # A failed constructor must give back its reservation — nobody
            # will call kill() on an actor that never came up.
            self._release_actor_resources(state)
            for queued in unfinished:
                self._store_error(queued, err)
            with self._lock:
                if state.name:
                    self._named_actors.pop((state.namespace, state.name),
                                           None)
            if state.name and self.gcs_store is not None:
                # A never-constructed actor must not be rebound after a
                # head restart (detached or not).
                self.gcs_store.remove_actor(state.actor_id.hex())
        with self._lock:
            if self._inflight.get(spec.task_id) is spec:
                self._inflight.pop(spec.task_id, None)
        self._return_worker(worker)
        self._dispatch()

    def submit_actor_task(self, spec: TaskSpec) -> List[ObjectRef]:
        group = spec.concurrency_group
        if group is not None:
            gstate = self._actors.get(spec.actor_id)
            if gstate is not None and \
                    group not in gstate.concurrency_groups:
                # Typos and group calls on group-less actors fail LOUDLY
                # (reference: unknown concurrency group raises) — silent
                # default-lane routing would fake isolation. Checked
                # BEFORE any ref registration so nothing leaks.
                raise ValueError(
                    f"Actor {spec.actor_id.hex()[:8]} has no concurrency "
                    f"group {group!r}; declared: "
                    f"{sorted(gstate.concurrency_groups) or 'none'}")
        from ray_tpu.util import tracing
        if tracing.is_tracing_enabled():
            # Same head-of-trace discipline as submit_task: the sampling
            # decision is made once here; unsampled calls stay bare.
            ctx = tracing.inject_context()
            if ctx is not None:
                import time as _time
                with tracing.continue_context(
                        ctx, "driver::submit",
                        {"stage": "submit", "task": spec.name,
                         "actor": spec.actor_id.hex()[:8]}) as span:
                    spec.trace_ctx = tracing.span_context(span)
                    spec._trace_submit_mono = _time.monotonic()  # type: ignore[attr-defined]
                    spec._trace_submit_wall = span.start_time  # type: ignore[attr-defined]
                    return self._submit_actor_task_inner(spec)
        return self._submit_actor_task_inner(spec)

    def _submit_actor_task_inner(self, spec: TaskSpec) -> List[ObjectRef]:
        n = max(spec.num_returns, 1) if spec.num_returns != "dynamic" else 1
        spec.return_ids = [
            ObjectID.for_return(spec.task_id, i + 1) for i in range(n)]
        refs = [ObjectRef(oid) for oid in spec.return_ids]
        if spec.num_returns == 0:
            refs = []
        self._register_task_refs(spec)
        state = self._actors.get(spec.actor_id)
        if state is None or state.dead:
            cause = state.death_cause if state else None
            self._store_error(spec, cause or ActorDiedError(
                spec.actor_id, f"Actor {spec.actor_id} is dead."))
            return refs
        with state.lock:
            if state.dead:
                self._store_error(spec, state.death_cause or
                                  ActorDiedError(spec.actor_id))
                return refs
            state.unfinished[spec.task_id] = spec
        self._record_event(spec, "SUBMITTED")
        self._resolve_dependencies(spec)
        return refs

    def _abort_actor_task_seq(self, spec: TaskSpec) -> None:
        """Mark a sealed-without-running actor task's sequence number as
        satisfied so later tasks on the same handle still execute."""
        state = self._actors.get(spec.actor_id)
        if state is None:
            return
        with state.lock:
            state.unfinished.pop(spec.task_id, None)
            handle = spec.caller_handle_id or "default"
            seq_state = state.seq_state.setdefault(
                handle, {"next": 1, "waiting": {}, "aborted": set()})
            seq_state.setdefault("aborted", set()).add(spec.sequence_number)
            self._drain_actor_seq(state, seq_state)

    def _drain_actor_seq(self, state: ActorState, seq_state: dict) -> None:
        """Submit all consecutively-ready tasks. Caller holds state.lock."""
        aborted = seq_state.setdefault("aborted", set())
        while True:
            nxt = seq_state["next"]
            if nxt in aborted:
                aborted.discard(nxt)
                seq_state["next"] += 1
                continue
            if nxt not in seq_state["waiting"]:
                return
            ready = seq_state["waiting"].pop(nxt)
            seq_state["next"] += 1
            if state.created.is_set() and state.executor is not None:
                self._submit_to_actor_executor(state.executor, ready,
                                               state)
            else:
                state.pre_creation_queue.append(ready)

    def _dispatch_actor_task(self, spec: TaskSpec) -> None:
        """Called when the task's deps are resolved. Enforces per-handle
        submission order: a task only reaches the executor when every earlier
        task from the same handle has (its deps resolved and) been enqueued."""
        state = self._actors.get(spec.actor_id)
        if state is None:
            self._store_error(spec, ActorDiedError(spec.actor_id))
            return
        with state.lock:
            if state.dead:
                state.unfinished.pop(spec.task_id, None)
                self._store_error(spec, state.death_cause or
                                  ActorDiedError(spec.actor_id))
                return
            handle = spec.caller_handle_id or "default"
            seq_state = state.seq_state.setdefault(
                handle, {"next": 1, "waiting": {}, "aborted": set()})
            seq_state["waiting"][spec.sequence_number] = spec
            self._drain_actor_seq(state, seq_state)

    def _submit_to_actor_executor(self, executor, spec: TaskSpec,
                                  state: ActorState) -> None:
        """Per-method concurrency-group routing (reference:
        concurrency_group_manager.h GetExecutor): tagged calls go to
        their group's sub-executor; untagged (or group-less actors) use
        the default path."""
        group = getattr(spec, "concurrency_group", None)
        if group is not None and hasattr(executor, "submit_group"):
            executor.submit_group(
                group, lambda s=spec: self._run_actor_task(s, state))
        else:
            executor.submit(lambda s=spec: self._run_actor_task(s, state))

    def _finish_actor_task(self, spec: TaskSpec, state: ActorState) -> None:
        with state.lock:
            state.unfinished.pop(spec.task_id, None)

    def _run_actor_task(self, spec: TaskSpec, state: ActorState):
        """Executes in the actor's executor. May return a coroutine (async
        actors) which the AsyncioActorExecutor awaits."""
        import asyncio
        if state.dead:
            self._store_error(spec, state.death_cause or
                              ActorDiedError(spec.actor_id))
            self._finish_actor_task(spec, state)
            return None
        try:
            from ray_tpu._private.multinode import RemoteActorInstance
            from ray_tpu._private.worker_process import ProcessActorInstance
            conn = None
            to_process = False
            if isinstance(state.instance, RemoteActorInstance):
                conn = state.instance.conn
                method = state.instance.bind_method(
                    spec.method_name, spec.name,
                    store_limit=self._result_store_limit(spec),
                    num_returns=(spec.num_returns if
                                 isinstance(spec.num_returns, int)
                                 else 1))
            elif isinstance(state.instance, ProcessActorInstance):
                to_process = True
                method = state.instance.bind_method(
                    spec.method_name, spec.name)
            else:
                method = getattr(state.instance, spec.method_name)
            args, kwargs = self._resolve_args(spec, conn,
                                              to_process=to_process)
        except BaseException as e:  # noqa: BLE001
            self._store_error(spec, TaskError(e, traceback.format_exc(),
                                              spec.name))
            self._finish_actor_task(spec, state)
            return None

        ctx = getattr(spec, "trace_ctx", None)
        if ctx is not None and \
                getattr(spec, "_trace_submit_mono", None) is not None:
            import time as _time
            from ray_tpu.util import tracing as _tr
            _tr.record_complete_span(
                "sched::queue_wait", ctx,
                wall_start=getattr(spec, "_trace_submit_wall", 0.0),
                duration=_time.monotonic() - spec._trace_submit_mono,
                attributes={"stage": "queue", "task": spec.name})

        if asyncio.iscoroutinefunction(method):
            async def _acall():
                try:
                    _task_context.spec = spec
                    try:
                        from ray_tpu.util import tracing
                        # Thread-local context on an asyncio loop:
                        # concurrent requests on one replica may see an
                        # interleaved ACTIVE span, but per-span parenting
                        # stays correct because the ctx rides the spec.
                        with tracing.continue_context(
                                getattr(spec, "trace_ctx", None),
                                f"actor_task::{spec.name}",
                                {"stage": "execute"}):
                            result = await method(*args, **kwargs)
                    finally:
                        _task_context.spec = None
                    self._store_results(spec, result)
                    self._record_event(spec, "FINISHED")
                except GeneratorExit:
                    # The garbage collector is closing a stale parked
                    # coroutine (its actor's loop died — possibly from an
                    # already-shut-down runtime). Touching runtime/native
                    # state from the collector's context deadlocks;
                    # kill_actor sealed this task's refs already.
                    raise
                except BaseException as e:  # noqa: BLE001
                    self._store_error(spec, TaskError(
                        e, traceback.format_exc(), spec.name))
                finally:
                    self._finish_actor_task(spec, state)
            return _acall()
        try:
            _task_context.spec = spec
            try:
                from ray_tpu.util import tracing
                with tracing.continue_context(
                        getattr(spec, "trace_ctx", None),
                        f"actor_task::{spec.name}", {"stage": "execute"}):
                    result = method(*args, **kwargs)
            finally:
                _task_context.spec = None
            self._store_results(spec, result)
            self._record_event(spec, "FINISHED")
        except BaseException as e:  # noqa: BLE001
            self._store_error(spec, TaskError(e, traceback.format_exc(),
                                              spec.name))
        finally:
            self._finish_actor_task(spec, state)
        return None

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        state = self._actors.get(actor_id)
        if state is None:
            return
        if not no_restart and (state.max_restarts == -1
                               or state.num_restarts < state.max_restarts):
            self._restart_actor(state)
            return
        with state.lock:
            if state.dead:
                return
            state.dead = True
            state.death_cause = ActorDiedError(
                actor_id, f"Actor {actor_id} was killed via kill().")
            state.created.set()
            if state.executor is not None:
                state.executor.stop()
            unfinished = list(state.unfinished.values())
            state.unfinished.clear()
            state.pre_creation_queue.clear()
        try:
            self._destroy_remote_instance(state)
        except Exception:  # noqa: BLE001 - best effort only
            pass
        # Seal every submitted-but-unfinished task so gets raise instead of
        # hanging (first-write-wins in the store keeps completed results).
        for spec in unfinished:
            self._store_error(spec, state.death_cause)
        with self._lock:
            # A creation task still queued never ran: drop + seal it here.
            if state.creation_spec in self._ready:
                self._ready.remove(state.creation_spec)
                self._store_error(state.creation_spec, state.death_cause)
        self._release_actor_resources(state)
        with self._lock:
            if state.name:
                self._named_actors.pop((state.namespace, state.name), None)
        if self.gcs_store is not None:
            self.gcs_store.remove_actor(actor_id.hex())
        self._dispatch()

    def _restart_actor(self, state: ActorState) -> None:
        """Restart an actor in place: stop the current instance, fail its
        in-flight tasks, and re-run the creation task on a fresh executor
        (reference: max_restarts semantics, gcs_actor_manager.h:88 — state is
        lost unless the actor checkpoints itself)."""
        from ray_tpu._private import builtin_metrics
        builtin_metrics.actor_restarts().inc(tags={"kind": "restart"})
        cause = ActorDiedError(
            state.actor_id,
            f"Actor {state.actor_id} is restarting; in-flight tasks failed.")
        try:
            self._destroy_remote_instance(state)
        except Exception:  # noqa: BLE001 - best effort only
            pass
        with state.lock:
            state.num_restarts += 1
            old_executor = state.executor
            state.executor = None
            state.instance = None
            state.created.clear()
            unfinished = list(state.unfinished.values())
            state.unfinished.clear()
            state.pre_creation_queue.clear()
            if old_executor is not None:
                old_executor.stop()
            # Sequence slots held by the failed tasks must not block the
            # restarted actor.
            for spec in unfinished:
                handle = spec.caller_handle_id or "default"
                seq_state = state.seq_state.setdefault(
                    handle, {"next": 1, "waiting": {}, "aborted": set()})
                if spec.sequence_number >= seq_state["next"]:
                    seq_state.setdefault("aborted", set()).add(
                        spec.sequence_number)
            for seq_state in state.seq_state.values():
                self._drain_actor_seq(state, seq_state)
        for spec in unfinished:
            self._store_error(spec, cause)
        if state.name and self.gcs_store is not None:
            # Burn down the persisted budget too: the count must survive
            # a head restart (detached actors keep restarting after one).
            self.gcs_store.update_actor(state.actor_id.hex(),
                                        num_restarts=state.num_restarts)
        # Re-run the creation task (a fresh TaskSpec attempt on the same
        # actor id); resources were never released, so dispatch reuses the
        # original reservation by running creation on a pool worker directly.
        creation = state.creation_spec
        worker = None
        with self._lock:
            worker = self._pop_worker()
        if worker is None:
            # Pool exhausted; queue through the normal path without
            # re-acquiring resources.
            worker = SerialThreadExecutor(
                WorkerID.from_random(), name="ray_tpu-restart")
            with self._lock:
                self._all_workers.append(worker)
        # Reset the creation return object is not possible (sealed); restart
        # success is observable via task results.
        worker.submit(lambda: self._run_actor_creation_restart(
            creation, worker, state))

    def _run_actor_creation_restart(self, spec: TaskSpec, worker: Executor,
                                    state: ActorState) -> None:
        try:
            cls = self.functions.load(spec.function_id)
            args, kwargs = self._resolve_args(spec, self._remote_conn(spec))
            instance = self._invoke_actor_init(spec, cls, args, kwargs)
            executor = self._make_actor_executor(state)
            with state.lock:
                if state.dead:
                    executor.stop()
                else:
                    state.instance = instance
                    state.executor = executor
                    state.created.set()
                    for queued in state.pre_creation_queue:
                        self._submit_to_actor_executor(executor, queued,
                                                       state)
                    state.pre_creation_queue.clear()
        except BaseException as e:  # noqa: BLE001
            if getattr(spec, "invalidated", False) or \
                    self._node_death_invalidated(spec, e):
                # The node died under the restarting __init__; node-death
                # handling owns the next restart attempt (including this
                # spec's dependency pins — don't double-release).
                self._return_worker(worker)
                self._dispatch()
                return
            err = TaskError(e, traceback.format_exc(), f"{spec.name}.restart")
            with state.lock:
                state.dead = True
                state.death_cause = err
                state.created.set()
                unfinished = list(state.unfinished.values())
                state.unfinished.clear()
            for queued in unfinished:
                self._store_error(queued, err)
            self._release_actor_resources(state)
        self._release_task_deps(spec)
        self._return_worker(worker)
        self._dispatch()

    def get_named_actor(self, name: str, namespace: str = "default") -> ActorID:
        with self._lock:
            actor_id = self._named_actors.get((namespace, name))
        if actor_id is None:
            raise ValueError(
                f"Failed to look up actor {name!r} in namespace {namespace!r}. "
                "It was either not created with a name or has died.")
        return actor_id

    def actor_state(self, actor_id: ActorID) -> Optional[ActorState]:
        return self._actors.get(actor_id)

    # ------------------------------------------------------------------
    # Cancellation
    # ------------------------------------------------------------------

    def cancel(self, ref: ObjectRef, force: bool = False) -> None:
        oid = ref.object_id()
        task_id = oid.task_id()
        with self._lock:
            for i, spec in enumerate(self._ready):
                if spec.task_id == task_id:
                    self._ready.pop(i)
                    self._store_error(spec, TaskCancelledError(task_id))
                    return
            for dq in self._ready_by_class.values():
                for spec in dq:
                    if spec.task_id == task_id:
                        dq.remove(spec)
                        self._store_error(spec, TaskCancelledError(task_id))
                        return
            for waiters in self._pending_by_oid.values():
                for pending in waiters:
                    if pending.spec.task_id == task_id:
                        pending.cancelled = True
                        self._store_error(pending.spec,
                                          TaskCancelledError(task_id))
                        if pending.spec.kind == TaskKind.ACTOR_TASK:
                            self._abort_actor_task_seq(pending.spec)
                        return
        # Running tasks: a task on a worker PROCESS is force-killable for
        # real — SIGKILL the worker, the blocked executor thread raises
        # and seals TaskCancelledError (reference: worker process kill on
        # ray.cancel(force=True)). Thread-backend tasks cannot be
        # interrupted; their result is discarded lazily.
        if force:
            # Kill UNDER the lock (non-blocking variant): the executing
            # thread pops _proc_tasks under this same lock before
            # releasing the worker to the pool, so the SIGKILL can never
            # land on a worker already re-leased to another task.
            with self._lock:
                handle = self._proc_tasks.get(task_id)
                spec = self._inflight.get(task_id)
                if handle is not None and spec is not None:
                    spec._cancel_requested = True  # type: ignore
                    handle.kill(wait=False)

    # ------------------------------------------------------------------
    # Placement groups
    # ------------------------------------------------------------------

    def create_placement_group(self, bundles: List[Dict[str, float]],
                               strategy: str = "PACK",
                               name: str = "") -> PlacementGroupID:
        pg_id = PlacementGroupID.from_random()
        self.scheduler.create_placement_group(pg_id, bundles, strategy)
        return pg_id

    def remove_placement_group(self, pg_id: PlacementGroupID) -> None:
        self.scheduler.remove_placement_group(pg_id)
        self._dispatch()

    # ------------------------------------------------------------------
    # Node membership (cluster_utils.Cluster / autoscaler entry points)
    # ------------------------------------------------------------------

    def add_node(self, resources: Dict[str, float],
                 labels: Optional[dict] = None) -> NodeID:
        node_id = self.scheduler.add_node(resources, labels=labels)
        # Bundles orphaned by an earlier node death land here if they fit.
        self.scheduler.reschedule_lost_bundles()
        self._dispatch()  # new capacity may unblock queued tasks
        self._maybe_spillback()  # ...or absorb misplaced daemon backlog
        return node_id

    def start_head_server(self, host: str = "127.0.0.1",
                          port: int = 0) -> Tuple[str, int]:
        """Open the head's TCP registration endpoint so node-daemon
        processes (`ray-tpu start --address host:port`) can join this
        cluster (reference: GCS server accepting raylet registration)."""
        with self._lock:
            if self._head_server is None:
                from ray_tpu._private.multinode import HeadServer
                server = HeadServer(self, host, port)
                server.start()
                self._head_server = server
        return self._head_server.address

    # -- internal KV (reference: gcs_kv_manager.h InternalKV) ----------

    def kv_put(self, namespace: str, key: bytes, value: bytes,
               overwrite: bool = True) -> bool:
        """Returns already_exists (reference internal_kv semantics)."""
        if self.gcs_store is not None:
            return self.gcs_store.kv_put(namespace, key, value, overwrite)
        with self._lock:
            ns = self._kv_mem.setdefault(namespace, {})
            existed = key in ns
            if overwrite or not existed:
                ns[key] = value
            return existed

    def kv_get(self, namespace: str, key: bytes):
        if self.gcs_store is not None:
            return self.gcs_store.kv_get(namespace, key)
        with self._lock:
            return self._kv_mem.get(namespace, {}).get(key)

    def kv_del(self, namespace: str, key: bytes) -> bool:
        if self.gcs_store is not None:
            return self.gcs_store.kv_del(namespace, key)
        with self._lock:
            return self._kv_mem.get(namespace, {}).pop(key, None) \
                is not None

    def kv_keys(self, namespace: str, prefix: bytes = b"") -> list:
        if self.gcs_store is not None:
            return self.gcs_store.kv_keys(namespace, prefix)
        with self._lock:
            return [k for k in self._kv_mem.get(namespace, {})
                    if k.startswith(prefix)]

    def new_node_id(self) -> "NodeID":
        """Pre-mint a node id (the handshake enqueues the 'registered'
        ack on the conn's sender BEFORE the node becomes schedulable, so
        the id must exist before register_remote_node runs)."""
        return NodeID.from_random()

    # ------------------------------------------------------------------
    # Log streaming fan-out (reference: worker.py print_logs subscribes
    # to the GCS log channel). Both paths converge on the "logs" pubsub
    # channel: JSON batches {pid, proc_name, source, task_name, lines,
    # node}; DriverLogPrinter (and anything else — tests, dashboards)
    # subscribes there.
    # ------------------------------------------------------------------

    def _membership_event(self, event: dict) -> None:
        """Membership fan-out sink (subscribed at init): node join/death
        events reach long-poll consumers on the "membership" pubsub
        channel keyed by node id — serve controllers and train executors
        react to a push instead of discovering death via their next
        failed RPC. Runs on the declarer's thread: publish only."""
        import json
        self.pubsub.publish("membership", str(event.get("node_id", "")),
                            json.dumps(event))
        # Journal the transition (head-local journal: direct append, no
        # piggyback latency). Joins are news; deaths are errors.
        kind = event.get("event", "")
        node_hex = str(event.get("node_id", ""))
        metrics = getattr(self, "_cluster_metrics", None)
        if metrics is None:  # an event before the pipeline exists
            return
        journal = metrics.events
        if kind == "joined":
            journal.record(
                "membership", f"node {node_hex[:12]} joined "
                f"(epoch {event.get('epoch')})",
                severity="info", node_id=node_hex,
                labels={"epoch": event.get("epoch", "")})
        elif kind == "dead":
            journal.record(
                "membership", f"node {node_hex[:12]} declared dead "
                f"({event.get('reason', 'unknown')}, "
                f"epoch {event.get('epoch')})",
                severity="error", node_id=node_hex,
                labels={"reason": event.get("reason", ""),
                        "epoch": event.get("epoch", "")})

    def _publish_log_batch(self, batch: dict) -> bool:
        """Head-local LogMonitor sink: stamp head identity, fan out."""
        import json
        msg = dict(batch)
        msg.setdefault("node", self.head_node_id.hex())
        self.pubsub.publish("logs", "", json.dumps(msg))
        return True

    def _log_batch_from_node(self, conn, msg: dict) -> None:
        """Wire sink for daemon-pushed log_batch frames (assigned to
        conn.on_log_batch at registration; runs on the conn's recv
        thread — publish only, no blocking work)."""
        import json
        batch = dict(msg)
        batch.pop("type", None)
        batch.pop("req_id", None)
        node = batch.pop("node_id", "")
        if not node and conn.node_id is not None:
            node = conn.node_id.hex()
        batch["node"] = node
        self.pubsub.publish("logs", "", json.dumps(batch))

    def _object_spilled_from_node(self, conn, msg: dict) -> None:
        """Wire sink for object_spilled frames: a daemon wrote this key
        through a DURABLE backend — the URI joins the location table so
        the daemon's death restores from disk instead of re-executing
        lineage (recv-thread: dict insert only). Bounded like the other
        location maps; past the cap recovery just falls down a tier."""
        recorded = False
        with self._lock:
            if len(self._spill_uris_by_key) < self._cfg_obj_loc_max:
                self._spill_uris_by_key[msg["key"]] = (
                    msg["uri"], int(msg.get("size", 0)))
                recorded = True
        # Spill URIs are the object directory's durable tier: mirror
        # them into the gcs_store so a REBORN head can still restore
        # from disk (head failover keeps tiered recovery working).
        if recorded and self.gcs_store is not None:
            try:
                self.gcs_store.record_spill_uri(
                    msg["key"], msg["uri"], int(msg.get("size", 0)))
            except OSError:
                logger.exception("could not persist spill URI")

    def _object_unspilled_from_node(self, conn, msg: dict) -> None:
        """Retraction: restore-promotion or a free deleted the file."""
        with self._lock:
            self._spill_uris_by_key.pop(msg["key"], None)
        if self.gcs_store is not None:
            try:
                self.gcs_store.remove_spill_uri(msg["key"])
            except OSError:
                logger.exception("could not retract spill URI")

    # ------------------------------------------------------------------
    # Cluster metrics (one Prometheus scrape for the whole cluster)
    # ------------------------------------------------------------------

    def _publish_head_metrics(self, batch: dict) -> bool:
        """Sink for this process's own metrics agent AND for batches its
        pool workers piggyback on task replies: merge locally under the
        head's node id."""
        self._cluster_metrics.update(self.head_node_id.hex(), batch)
        return True

    def _metrics_batch_from_node(self, conn, msg: dict) -> None:
        """Wire sink for daemon-pushed metrics_batch frames (assigned to
        conn.on_metrics_batch at registration; recv-thread — merge is a
        dict update, no blocking work)."""
        node = msg.get("node_id") or ""
        if not node and conn.node_id is not None:
            node = conn.node_id.hex()
        self._cluster_metrics.update(node, msg)

    def _publish_head_profile(self, batch: dict) -> bool:
        """Sink for the head profiler's windows AND for windows head
        pool workers piggyback on task replies: straight into the
        profile store under the head's node id."""
        self._cluster_metrics.update_profile(self.head_node_id.hex(),
                                             batch)
        return True

    def _profile_batch_from_node(self, conn, msg: dict) -> None:
        """Wire sink for daemon-pushed profile_batch frames (assigned to
        conn.on_profile_batch at registration; recv-thread — merge is a
        dict update, no blocking work)."""
        node = msg.get("node_id") or ""
        if not node and conn.node_id is not None:
            node = conn.node_id.hex()
        self._cluster_metrics.update_profile(node, msg)

    def _publish_head_flow(self, batch: dict) -> bool:
        """Sink for the head's own transfer-ledger drains AND for
        batches head pool workers piggyback on task replies: straight
        into the flow store under the head's node id."""
        self._cluster_metrics.update_flows(self.head_node_id.hex(),
                                           batch)
        return True

    def _flow_batch_from_node(self, conn, msg: dict) -> None:
        """Wire sink for daemon-pushed flow_batch frames (assigned to
        conn.on_flow_batch at registration; recv-thread — ingestion is
        bounded dict work, no blocking)."""
        node = msg.get("node_id") or ""
        if not node and conn.node_id is not None:
            node = conn.node_id.hex()
        self._cluster_metrics.update_flows(node, msg)

    def _collect_head_metrics(self) -> None:
        """Refresh head-side gauges right before each export snapshot —
        level-style series (queue depth, store bytes, pool size, actor
        count) cost nothing on the hot paths this way."""
        from ray_tpu._private import builtin_metrics, scheduler as _sched
        with self._lock:
            pending = sum(1 for _ in self._ready_specs_locked())
            actors = sum(1 for a in self._actors.values() if not a.dead)
        _sched.record_queue_depth(pending)
        builtin_metrics.actors_gauge().set(actors)
        record = getattr(self.scheduler, "record_metrics", None)
        if record is not None:  # native scheduler variant may lack it
            record()
        self.store.record_metrics()
        pool = self._process_pool
        if pool is not None:
            pool.record_metrics()

    def cluster_metrics_text(self) -> str:
        """The cluster-wide Prometheus exposition: a fresh head snapshot
        merged with the latest daemon/worker batches (remote origins are
        as fresh as their export interval)."""
        agent = self._metrics_agent
        if agent is not None:  # None after shutdown(): render what's held
            try:
                agent.poll_once()
            except Exception:  # noqa: BLE001 - scrape must not fail on this
                logger.exception("head metrics poll failed")
        return self._cluster_metrics.render()

    def cluster_chrome_spans(self) -> List[dict]:
        """Remote worker/daemon spans (shipped in metrics_batch frames)
        as chrome://tracing events for /api/timeline."""
        return self._cluster_metrics.chrome_spans()

    def _flush_trace_spans(self) -> None:
        """Pull this process's pending finished spans into the assembler
        before a trace read — remote origins stay as fresh as their
        export interval, but the head's own spans need not wait a tick."""
        agent = self._metrics_agent
        if agent is not None:
            try:
                agent.poll_once()
            except Exception:  # noqa: BLE001 - reads must not fail on this
                logger.exception("head trace flush failed")

    def trace_list(self, limit: Optional[int] = None) -> List[dict]:
        self._flush_trace_spans()
        return self._cluster_metrics.traces.list_traces(limit)

    def trace_get(self, trace_id: str) -> Optional[dict]:
        self._flush_trace_spans()
        return self._cluster_metrics.traces.get_trace(trace_id)

    def trace_summary(self) -> dict:
        self._flush_trace_spans()
        return self._cluster_metrics.traces.summary()

    def trace_perfetto(self, trace_id: Optional[str] = None) -> List[dict]:
        self._flush_trace_spans()
        return self._cluster_metrics.traces.perfetto(trace_id)

    def trace_flow_events(self) -> List[dict]:
        """Cross-process flow (s/f) arrows for /api/timeline."""
        self._flush_trace_spans()
        return self._cluster_metrics.traces.flow_events()

    # -- time-series signal plane (timeseries.py) ----------------------

    def get_timeseries(self, name: str,
                       labels: Optional[Dict[str, str]] = None,
                       window: Optional[float] = None,
                       step: Optional[float] = None) -> dict:
        """Windowed history + per-series summaries (reset-safe counter
        rates, gauge last/avg, histogram p50/p95) for one metric from
        the head's time-series store. The head's own registry is polled
        first so driver-side series are as fresh as the call."""
        self._flush_trace_spans()  # poll_once: fold + snapshot head
        return self._cluster_metrics.timeseries.query(
            name, labels=labels, window=window, step=step)

    def serve_stats(self, window: Optional[float] = None) -> dict:
        """Per-deployment traffic rollup over ``window`` seconds (default
        30): qps, p50/p95/mean latency, mean queue depth, replica count.
        The drop-in input for a metrics-driven replica autoscaler."""
        self._flush_trace_spans()
        w = 30.0 if window is None else float(window)
        ts = self._cluster_metrics.timeseries
        qps = ts.counter_rate("ray_tpu_serve_requests_total",
                              window=w, group_by="deployment")
        lat = ts.histogram_stats("ray_tpu_serve_request_latency_seconds",
                                 window=w, group_by="deployment")
        queue = ts.gauge_stats("ray_tpu_serve_queue_depth",
                               window=w, group_by="deployment")
        replicas = ts.gauge_stats("ray_tpu_serve_replicas",
                                  window=w, group_by="deployment")
        targets = ts.gauge_stats("ray_tpu_serve_target_replicas",
                                 window=w, group_by="deployment")
        deployments = {}
        for name in (set(qps) | set(lat) | set(queue) | set(replicas)):
            if not name:
                continue
            h = lat.get(name, {})
            tgt = targets.get(name, {}).get("last_max")
            deployments[name] = {
                "qps": qps.get(name, 0.0),
                "p50_s": h.get("p50", 0.0),
                "p95_s": h.get("p95", 0.0),
                "mean_latency_s": h.get("mean", 0.0),
                "requests": h.get("count", 0),
                # Queue depths are additive across routers; replica
                # counts are replicated views — max, not sum.
                "mean_queue_depth": queue.get(name, {}).get("avg_sum", 0.0),
                "replicas": int(replicas.get(name, {}).get("last_max", 0)),
                # Autoscaler-set target (None: not an autoscaled
                # deployment, or no autoscale pass in the window yet).
                "target_replicas": None if tgt is None else int(tgt),
            }
        return {"window_s": w, "deployments": deployments}

    def membership_snapshot(self) -> List[dict]:
        """Read-only membership internals (epoch / phi / heartbeat age)
        per live node, for status surfaces."""
        return self.membership.snapshot()

    def cluster_event_stats(self) -> Dict[str, dict]:
        """EventStats summaries shipped inside metrics_batch frames,
        keyed ``"<node_id>:<component>"`` (daemon control loops)."""
        return self._cluster_metrics.cluster_event_stats()

    def top_snapshot(self, window: Optional[float] = None) -> dict:
        """One `ray-tpu top` frame, rendered entirely from windowed
        store history: per-node usage + membership + task rates, object
        store bytes/spill rate, per-deployment serve stats, control-loop
        lag gauges."""
        self._flush_trace_spans()
        w = 30.0 if window is None else float(window)
        ts = self._cluster_metrics.timeseries
        node_rates: Dict[str, Dict[str, float]] = {}
        for status in ("SUBMITTED", "FINISHED", "FAILED"):
            rates = ts.counter_rate(
                "ray_tpu_node_task_events_total",
                labels={"status": status}, window=w, group_by="node_id")
            for node_hex, rate in rates.items():
                node_rates.setdefault(node_hex, {})[status.lower()] = rate
        usage = {}
        srv = getattr(self, "_head_server", None)
        if srv is not None:
            usage = srv.syncer.digest().get("nodes", {})
        membership = {row["node_id"]: row
                      for row in self.membership.snapshot()}
        nodes = []
        for node in self.scheduler.nodes_snapshot():
            hexid = node.get("NodeID", "")
            live = membership.get(hexid, {})
            used = usage.get(hexid, {})
            rates = node_rates.get(hexid, {})
            nodes.append({
                "node_id": hexid,
                "alive": node.get("Alive", False),
                "resources": node.get("Resources", {}),
                "epoch": live.get("epoch"),
                "phi": live.get("phi"),
                "last_heartbeat_age_s": live.get("last_heartbeat_age_s"),
                "rss_bytes": used.get("memory", {}).get("rss_bytes"),
                "object_store": used.get("object_store", {}),
                "resource_load": used.get("resource_load", {}),
                "tasks_submitted_per_s": rates.get("submitted", 0.0),
                "tasks_finished_per_s": rates.get("finished", 0.0),
                "tasks_failed_per_s": rates.get("failed", 0.0),
            })
        tasks = {
            "submitted_per_s": sum(ts.counter_rate(
                "ray_tpu_tasks_submitted_total", window=w).values()),
            "finished_per_s": sum(ts.counter_rate(
                "ray_tpu_tasks_finished_total", window=w).values()),
            "failed_per_s": sum(ts.counter_rate(
                "ray_tpu_tasks_failed_total", window=w).values()),
        }
        objects = {
            "store_bytes": ts.gauge_stats(
                "ray_tpu_object_store_bytes",
                window=w).get("", {}).get("last_sum", 0.0),
            "spill_bytes_per_s": sum(ts.counter_rate(
                "ray_tpu_object_spilled_bytes_total", window=w).values()),
            "restores_per_s": sum(ts.counter_rate(
                "ray_tpu_object_restores_total", window=w).values()),
        }
        loops = {
            key: stats["last_max"]
            for key, stats in ts.gauge_stats(
                "ray_tpu_loop_lag_seconds", window=w,
                group_by="loop").items() if key}
        # Firing alerts ride the same snapshot so `ray-tpu top`'s banner
        # costs no extra round-trip (evaluation is period-gated).
        cm = self._cluster_metrics
        try:
            cm.alerts.maybe_evaluate(ts)
        except Exception:  # noqa: BLE001 - a bad rule must not break top
            logger.exception("alert evaluation in top_snapshot failed")
        firing = cm.alerts.firing()
        return {
            "window_s": w,
            "nodes": nodes,
            "tasks": tasks,
            "objects": objects,
            "serve": self.serve_stats(window=w)["deployments"],
            "loops": loops,
            "transfer": cm.flows.summary_line(),
            "alerts": {
                "firing": firing,
                "firing_count": len(firing),
                "rules": [a["rule"] for a in firing],
            },
            "timeseries": {
                "series": ts.series_count(),
                "dropped_series": ts.dropped_series,
            },
        }

    # -- alerting plane + cluster event journal --------------------------

    def alerts_snapshot(self) -> dict:
        """Active alert instances, rule table, and firing history from
        the head's alert engine. The head's own registry is polled
        first (fresh head samples) and an evaluation is forced so the
        answer reflects the store as of this call, not the last merge
        tick."""
        self._flush_trace_spans()
        cm = self._cluster_metrics
        try:
            cm.alerts.maybe_evaluate(cm.timeseries)
        except Exception:  # noqa: BLE001 - reads must not fail on eval
            logger.exception("alert evaluation on read failed")
        return cm.alerts.snapshot()

    def add_alert_rule(self, rule) -> None:
        """Install (or replace, by name) a user alert rule — an
        ``alerting.AlertRule`` / ``BurnRateRule`` instance."""
        self._cluster_metrics.alerts.add_rule(rule)

    def remove_alert_rule(self, name: str) -> bool:
        return self._cluster_metrics.alerts.remove_rule(name)

    def subscribe_alerts(self, fn) -> None:
        """``fn(alert_dict)`` on every firing/resolved transition (the
        serve controller's scale_hint hook)."""
        self._cluster_metrics.alerts.subscribe(fn)

    def cluster_events(self, severity: Optional[str] = None,
                       source: Optional[str] = None,
                       node_id: Optional[str] = None,
                       since_seq: Optional[int] = None,
                       limit: Optional[int] = None) -> List[dict]:
        """Filtered journal rows (oldest first, ``age_s`` stamped). The
        head agent is polled first so head-emitted events don't wait an
        export tick."""
        self._flush_trace_spans()
        return self._cluster_metrics.events.query(
            severity=severity, source=source, node_id=node_id,
            since_seq=since_seq, limit=limit)

    def cluster_events_stats(self) -> dict:
        return self._cluster_metrics.events.stats()

    def cluster_event_annotations(self, limit: int = 200) -> List[dict]:
        """Grafana annotations-style feed derived from the journal."""
        self._flush_trace_spans()
        return self._cluster_metrics.events.annotations(limit=limit)

    # -- continuous profiling plane (profile_store.py) ------------------

    def profile_flame(self, component: Optional[str] = None,
                      node: Optional[str] = None,
                      window: Optional[float] = None,
                      fmt: str = "folded"):
        """Merged cluster/per-component flamegraph from the continuous
        windows ('folded' | 'speedscope' | 'dict'). The head's own
        profiler is drained first so driver stacks are as fresh as the
        call."""
        self._flush_trace_spans()  # poll_once also ships head profiles
        return self._cluster_metrics.profiles.flame(
            component=component, node_id=node, window=window, fmt=fmt)

    def profile_diff(self, window: float = 60.0,
                     component: Optional[str] = None,
                     node: Optional[str] = None,
                     limit: int = 50) -> List[dict]:
        """Window-vs-window stack diff ("what got hot")."""
        self._flush_trace_spans()
        return self._cluster_metrics.profiles.diff(
            window=window, component=component, node_id=node,
            limit=limit)

    def profile_incidents(self) -> List[dict]:
        """The loop-lag flight recorder's incident ring, newest first."""
        return self._cluster_metrics.profiles.incidents()

    def profile_stats(self) -> dict:
        return self._cluster_metrics.profiles.stats()

    # -- dataplane flow plane (flow.py) ---------------------------------

    def broadcast(self, ref: ObjectRef,
                  fanout: Optional[int] = None) -> dict:
        """Replicate one object onto every live daemon through a
        bounded-fanout spanning tree (reference: collective broadcast —
        the head stops being the serial source). A daemon-owned object
        roots the tree at its holder; a head-resident one seeds only its
        ``fanout`` direct children inline (head egress = fanout x size,
        flat in cluster width) and every deeper node waits on its
        parent's object server and pulls node-to-node. Blocks until the
        whole tree settles; returns a summary dict (nodes, depth,
        edges)."""
        return self._broadcast_object(ref.object_id(), fanout=fanout)

    def _broadcast_object(self, oid: ObjectID,
                          fanout: Optional[int] = None) -> dict:
        import time as _time
        from ray_tpu._private.multinode import _dumps
        fanout = max(1, int(fanout if fanout is not None
                            else self.config.broadcast_fanout))
        t_start = _time.monotonic()
        with self._lock:
            rv = self._remote_values.get(oid)
            conns = {nid: c for nid, c in self._remote_nodes.items()
                     if getattr(c, "object_addr", None) is not None}
            holders = set(self._object_replicas.get(oid) or ())
        payload = None
        root_id = None
        root_addr = None
        if rv is not None:
            root_id, key = rv
            holders.add(root_id)
            size = self.store.size_of(oid)
            root_conn = conns.get(root_id)
            if root_conn is None:
                raise ValueError(
                    f"cannot broadcast {oid.hex()[:12]}: its holder "
                    "node is not connected")
        else:
            # Head-resident: serialize once, seed direct children with
            # the bytes inline (the head has no object server to pull
            # from), deeper nodes cascade peer-to-peer.
            payload = _dumps(self.store.get(oid))
            size = len(payload)
            key = f"bcast-{oid.hex()}"
        targets = [nid for nid in conns if nid not in holders]
        summary = {"key": key, "size": size, "fanout": fanout,
                   "nodes": 0, "depth": 0, "edges": []}
        if not targets:
            return summary

        def addr(nid):
            return tuple(conns[nid].object_addr)

        if root_id is not None:
            root_addr = addr(root_id)
        # Array-indexed k-ary tree over [root?] + targets: parent of
        # position p is (p-1)//fanout. Head-rooted trees have no
        # position 0 holder — the first `fanout` targets sit at depth 1
        # (seeded inline) and position i parents onto (i-fanout)//fanout.
        plan = []  # (nid, parent_addr|None, alts, depth)
        depth_of: Dict[int, int] = {}
        root_alt = root_addr if root_id is not None else addr(targets[0])
        for i, nid in enumerate(targets):
            if root_id is not None:
                pos = i + 1
                parent_pos = (pos - 1) // fanout
                parent = (root_addr if parent_pos == 0
                          else addr(targets[parent_pos - 1]))
                gp_pos = (parent_pos - 1) // fanout
                grandp = (None if parent_pos == 0 else
                          root_addr if gp_pos == 0
                          else addr(targets[gp_pos - 1]))
                depth = depth_of[pos] = \
                    depth_of.get(parent_pos, 0) + 1
            elif i < fanout:
                parent = grandp = None  # head-seeded, depth 1
                depth = depth_of[i] = 1
            else:
                parent_i = (i - fanout) // fanout
                parent = addr(targets[parent_i])
                grandp = (addr(targets[(parent_i - fanout) // fanout])
                          if parent_i >= fanout else None)
                depth = depth_of[i] = depth_of[parent_i] + 1
            # Re-parenting ladder for a mid-tree death: grandparent
            # first, then the tree root — one failover per orphaned
            # subtree, never a dead broadcast.
            me = addr(nid)
            alts = [a for a in (grandp, root_alt)
                    if a is not None and a != parent and a != me]
            alts = list(dict.fromkeys(alts))
            plan.append((nid, parent, alts, depth))
        results: Dict[NodeID, Optional[dict]] = {}
        res_lock = threading.Lock()

        def _one(nid, parent, alts, depth):
            try:
                if parent is None and payload is not None:
                    r = conns[nid].push_object(key, size, data=payload)
                else:
                    r = conns[nid].push_object(
                        key, size, parent=parent, alts=alts,
                        wait_timeout_s=30.0 + 15.0 * depth)
            except Exception as exc:  # noqa: BLE001 - per-edge failure
                logger.warning("broadcast push of %s to node %s failed:"
                               " %s", key, nid.hex()[:12], exc)
                r = None
            with res_lock:
                results[nid] = r

        threads = [threading.Thread(target=_one, args=p, daemon=True,
                                    name=f"broadcast-edge-{i}")
                   for i, p in enumerate(plan)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        hex_of = {addr(nid): nid.hex() for nid in conns}
        edges = []
        for nid, parent, _alts, depth in plan:
            r = results.get(nid)
            edges.append({
                "src": ("head" if parent is None and root_id is None
                        else hex_of.get(parent, "?")),
                "dst": nid.hex(), "depth": depth, "ok": r is not None,
                "bytes": 0 if r is None else size,
                "failovers": 0 if r is None else r.get("failovers", 0),
                "secs": None if r is None else r.get("secs"),
            })
        ok_nodes = [nid for nid, r in results.items() if r is not None]
        with self._lock:
            if root_id is None and ok_nodes and \
                    oid not in self._remote_values:
                # The object now lives on daemons too: future consumers
                # get replica markers instead of head-inlined payloads.
                self._remote_values[oid] = (ok_nodes[0], key)
                self._remote_keys[key] = oid
                self._broadcasted[oid] = None
            for nid in ok_nodes:
                if (root_id is None or nid != root_id) and \
                        len(self._object_replicas) < \
                        self._cfg_obj_loc_max:
                    self._object_replicas.setdefault(oid, {})[nid] = None
        if self.gcs_store is not None:
            try:
                for nid in ok_nodes:
                    self.gcs_store.record_object_replica(
                        oid.hex(), nid.hex())
            except OSError:
                pass
        builtin_metrics.broadcast_trees().inc()
        if ok_nodes:
            builtin_metrics.push_bytes().inc(size * len(ok_nodes))
        summary.update(
            nodes=len(ok_nodes),
            depth=max((e["depth"] for e in edges), default=0),
            edges=edges, root=(root_id.hex() if root_id else "head"),
            duration_s=_time.monotonic() - t_start)
        self._cluster_metrics.flows.note_broadcast(summary)
        return summary

    def flows_snapshot(self, window: Optional[float] = None) -> dict:
        """The per-link transfer matrix + per-object fan-out table
        (`/api/flows`, `ray-tpu xfer`). The head's own ledger is
        drained first so driver-side pulls are as fresh as the call."""
        self._flush_trace_spans()  # poll_once also ships head flows
        return self._cluster_metrics.flows.snapshot(window=window)

    def flow_stats(self) -> dict:
        return self._cluster_metrics.flows.stats()

    def profile_cluster(self, duration: float = 10.0, hz: int = 100,
                        fmt: str = "folded"):
        """Synchronized on-demand burst: fan a profile request to every
        live daemon IN PARALLEL while the head samples itself, and merge
        the folded stacks with ``component@node/pid`` roots (same shape
        as the continuous store's flame output)."""
        from ray_tpu._private.profiling import (folded_to_speedscope,
                                                sample_self)
        with self._lock:
            conns = dict(self._remote_nodes)
        merged: Dict[str, int] = {}
        merge_lock = threading.Lock()
        head_hex = self.head_node_id.hex()[:8]

        def _merge(root: str, counts: Dict[str, int]) -> None:
            with merge_lock:
                for stack, n in counts.items():
                    key = f"{root};{stack}"
                    merged[key] = merged.get(key, 0) + int(n)

        def _one_node(node_id, conn):
            try:
                counts = conn.profile(duration=duration, hz=hz,
                                      fmt="dict")
            except Exception:  # noqa: BLE001 - a dead node skips the burst
                logger.exception("profile burst failed for node %s",
                                 node_id.hex()[:8])
                return
            _merge(f"daemon@{node_id.hex()[:8]}/0", counts or {})

        threads = [threading.Thread(target=_one_node, args=(nid, conn),
                                    daemon=True,
                                    name=f"profile-burst-{i}")
                   for i, (nid, conn) in enumerate(conns.items())]
        for t in threads:
            t.start()
        _merge(f"driver@{head_hex}/{os.getpid()}",
               sample_self(duration, hz))
        for t in threads:
            t.join(timeout=duration + 60)
        if fmt == "dict":
            return merged
        if fmt == "speedscope":
            return folded_to_speedscope(merged, name="ray_tpu-burst",
                                        hz=hz)
        return "\n".join(f"{k} {v}"
                         for k, v in sorted(merged.items()))

    def profile_pid(self, pid: int, duration: float = 5.0,
                    hz: int = 100, fmt: str = "folded"):
        """Profile one process of the cluster by pid: the head itself,
        a head pool worker over its request pipe, or any daemon-owned
        worker via the owning daemon's burst endpoint (``--pid``
        without py-spy). Daemons are tried in turn — the one that knows
        the pid answers; the rest raise and are skipped."""
        from ray_tpu._private.profiling import (folded_to_speedscope,
                                                profile_self, sample_self)
        if int(pid) == os.getpid():
            return profile_self(duration, hz, fmt)
        pool = self._process_pool
        if pool is not None:
            for w in list(pool._all):
                if w.pid == int(pid) and not w.dead:
                    reply = w.request(
                        {"type": "profile", "duration": duration,
                         "hz": hz}, timeout=duration + 30)
                    if not reply.get("ok"):
                        raise RuntimeError(reply.get("error")
                                           or "worker profile failed")
                    counts = reply.get("stacks") or {}
                    if fmt == "dict":
                        return counts
                    if fmt == "speedscope":
                        return folded_to_speedscope(
                            counts, name=f"worker-{pid}", hz=hz)
                    return "\n".join(
                        f"{k} {v}" for k, v in sorted(counts.items()))
        with self._lock:
            conns = list(self._remote_nodes.items())
        errors = []
        for node_id, conn in conns:
            try:
                return conn.profile(duration=duration, hz=hz, fmt=fmt,
                                    pid=int(pid))
            except Exception as exc:  # noqa: BLE001 - not this node's pid
                errors.append(f"{node_id.hex()[:8]}: {exc}")
        detail = "; ".join(errors) if errors else "no live daemons"
        raise ValueError(
            f"pid {pid} is not a known worker/daemon of this cluster "
            f"({detail})")

    def register_remote_node(self, conn, info: Optional[dict] = None,
                             dispatch: bool = True,
                             node_id: Optional["NodeID"] = None) -> NodeID:
        # The connection must be visible BEFORE dispatch can place tasks
        # on the new node — otherwise a queued task assigned to it would
        # find no conn and silently run head-local.
        node_id = self.scheduler.add_node(dict(conn.resources),
                                          labels=conn.labels,
                                          node_id=node_id)
        # Daemon-pushed log/metrics batches flow into the driver fan-out
        # and the cluster metrics registry; durable-spill announcements
        # feed the object location table for tiered recovery.
        conn.on_log_batch = self._log_batch_from_node
        conn.on_metrics_batch = self._metrics_batch_from_node
        conn.on_profile_batch = self._profile_batch_from_node
        conn.on_flow_batch = self._flow_batch_from_node
        conn.on_object_spilled = self._object_spilled_from_node
        conn.on_object_unspilled = self._object_unspilled_from_node
        # Teach the flow store the node's object-server address so the
        # holder addresses in pull records resolve to node ids (link
        # matrix cells read node->node, not host:port->node).
        if getattr(conn, "object_addr", None):
            self._cluster_metrics.flows.note_node(
                node_id.hex(), conn.object_addr)
        with self._lock:
            self._remote_nodes[node_id] = conn
        # A daemon reconnecting to a RESTARTED head announces the actor
        # instances it still hosts; rebind the persisted named ones so
        # get_actor(name) answers again (reference: GCS restart +
        # RayletNotifyGCSRestart resubscription). EXCEPT when the
        # daemon's previous incarnation was fenced (declared dead after
        # a partition): those residents died exactly once with that
        # incarnation — a restarted copy may already run elsewhere, so
        # rebinding (or even leaving) the stale instances would
        # double-run detached-actor side effects. Destroy them instead.
        residents = (info or {}).get("resident_actors") or []
        prev_epoch = int((info or {}).get("prev_epoch") or 0)
        if residents and prev_epoch and \
                self.membership.is_fenced(prev_epoch):
            logger.warning(
                "Node %s re-registered from fenced incarnation %d: "
                "destroying %d stale resident actor(s) instead of "
                "rebinding", node_id.hex()[:12], prev_epoch,
                len(residents))
            stale_ids = [ActorID(bytes.fromhex(h)) for h in residents]
            # Deferred: the handshake path calls with dispatch=False and
            # the registration ack must reach the daemon first (see the
            # stale-name destroy below for the same pattern).
            threading.Thread(
                target=lambda: [conn.destroy_actor(aid)
                                for aid in stale_ids],
                name="ray_tpu-fenced-actor-destroy", daemon=True).start()
        else:
            unrecoverable = []
            for actor_hex in residents:
                try:
                    if not self._rebind_remote_actor(conn, node_id,
                                                     actor_hex):
                        unrecoverable.append(actor_hex)
                except Exception:  # noqa: BLE001 - best effort per actor
                    logger.exception("failed to rebind actor %s",
                                     actor_hex)
            if unrecoverable and self.gcs_store is not None:
                # Residents with no surviving record (e.g. serve
                # replicas of the dead head's generation, whose records
                # the recovery retired) are zombies: nothing can ever
                # route to them again, but they'd keep holding the
                # daemon's resources. Destroy them — deferred for the
                # same ack-ordering reason as the fenced path above.
                logger.warning(
                    "Node %s announced %d resident actor(s) with no "
                    "surviving record: destroying", node_id.hex()[:12],
                    len(unrecoverable))
                dead_ids = [ActorID(bytes.fromhex(h))
                            for h in unrecoverable]
                threading.Thread(
                    target=lambda: [conn.destroy_actor(aid)
                                    for aid in dead_ids],
                    name="ray_tpu-unrecoverable-actor-destroy",
                    daemon=True).start()
        self.scheduler.reschedule_lost_bundles()
        if dispatch:
            # NOT under the caller's conn._send_lock (the handshake path
            # passes dispatch=False): task sends are inline, and sending
            # on a connection whose send lock the caller already holds
            # would self-deadlock.
            self._dispatch()
        return node_id

    def _rebind_remote_actor(self, conn, node_id: NodeID,
                             actor_hex: str) -> bool:
        """Rebind one daemon-announced resident actor. Returns True when
        the resident stays valid (rebound, same-life refresh, or handled
        another way); False means no record survives for it and the
        caller should destroy the zombie instance."""
        from ray_tpu._private.multinode import RemoteActorInstance
        rec = (self.gcs_store.actors.get(actor_hex)
               if self.gcs_store is not None else None)
        if rec is None:
            # Not a persisted actor (or persistence disabled). With a
            # store attached, "no record" means retired/unrecoverable.
            return self.gcs_store is None
        actor_id = ActorID(bytes.fromhex(actor_hex))
        cls_bytes = rec.get("cls_bytes")
        if cls_bytes is not None:
            # Export BEFORE taking the runtime lock (the function table
            # has its own locking); an orphan export on the bail-out
            # paths below is harmless.
            fn_id = self.functions.export_bytes(cls_bytes)
        resources = dict(rec.get("resources") or {})
        stale = False
        with self._lock:
            existing = self._actors.get(actor_id)
            if existing is not None and not existing.dead:
                # Same-life daemon reconnect: refresh the wire proxy and
                # the placement so node-death handling tracks the NEW
                # connection.
                existing.instance = RemoteActorInstance(conn, actor_id)
                existing.creation_spec._node_id = node_id  # type: ignore
                return True
            if existing is not None:
                # Died in this head's eyes; do not resurrect — and tell
                # the caller so the zombie instance is torn down.
                return False
            name_owner = self._named_actors.get(
                (rec["namespace"], rec["name"])) if rec["name"] else None
            if name_owner is not None and name_owner != actor_id:
                stale = True  # handled below, outside the lock
            elif cls_bytes is None:
                # Unpicklable class: handles cannot be rebuilt, but the
                # instance is alive and harmless — leave it be.
                return True
            else:
                # Name check and registration happen under ONE lock
                # acquisition: a concurrent create_actor can never claim
                # the name between our check and our insert.
                lifetime = rec.get("lifetime")
                creation_args: tuple = ()
                creation_kwargs: dict = {}
                max_restarts = 0
                if lifetime == "detached":
                    # Detached records carry the pickled __init__ args,
                    # so the rebound actor keeps its FULL restart budget
                    # — a later node death re-runs the creation
                    # elsewhere. Undecodable payload degrades to
                    # rebind-without-restart (max_restarts=0), matching
                    # plain named actors.
                    payload = rec.get("creation_payload")
                    if payload is not None:
                        try:
                            creation_args, creation_kwargs = \
                                serialization.deserialize(payload)
                            max_restarts = rec["max_restarts"]
                        except Exception:  # noqa: BLE001
                            creation_args, creation_kwargs = (), {}
                spec = TaskSpec(
                    task_id=TaskID.for_normal_task(self.job_id),
                    kind=TaskKind.ACTOR_CREATION, function_id=fn_id,
                    args=creation_args, kwargs=creation_kwargs,
                    resources=resources,
                    num_returns=1, name=rec["name"] or "actor",
                    actor_id=actor_id)
                # The creation never re-runs on THIS head unless the
                # node dies — but the restart clone goes through the
                # normal creation path, which seals return_ids[0].
                spec.return_ids = [ObjectID.for_return(spec.task_id, 1)]
                # Node-death bookkeeping must see where the instance
                # lives, and release needs the acquire marker.
                spec._node_id = node_id  # type: ignore[attr-defined]
                spec._acquired_bundle = -1  # type: ignore[attr-defined]
                # Non-detached rebound actors cannot be restarted in
                # place (their creation args died with the old head) —
                # max_restarts=0.
                state = ActorState(actor_id, spec, max_restarts,
                                   rec["max_concurrency"],
                                   rec["name"], rec["namespace"],
                                   concurrency_groups=rec.get(
                                       "concurrency_groups"),
                                   lifetime=lifetime)
                state.num_restarts = int(rec.get("num_restarts") or 0)
                state.instance = RemoteActorInstance(conn, actor_id)
                state.executor = self._make_actor_executor(state)
                state.created.set()
                self._actors[actor_id] = state
                if rec["name"]:
                    self._named_actors[(rec["namespace"], rec["name"])] = \
                        actor_id
        if stale:
            # A NEW actor took this name on the restarted head before
            # the old daemon reconnected — the live one wins; drop the
            # stale record and tear down the zombie instance.
            logger.warning(
                "Not rebinding stale actor %s: name %r is taken by a "
                "newer actor", actor_hex[:12], rec["name"])
            if self.gcs_store is not None:
                self.gcs_store.remove_actor(actor_hex)
            # Deferred: the handshake thread holds conn._send_lock (the
            # ack must be the daemon's first frame) and destroy_actor
            # sends on that same non-reentrant lock — a direct call here
            # deadlocks the registration. The helper thread parks on the
            # lock and the destroy frame goes out right after the ack.
            threading.Thread(
                target=lambda: conn.destroy_actor(actor_id),
                name="ray_tpu-stale-actor-destroy", daemon=True).start()
            return True
        # The resident instance still consumes its creation resources on
        # that node — re-reserve them so the restarted head cannot
        # double-book the chips/CPUs (force: the node just (re)joined
        # advertising its FULL capacity, and the actor's claim predates
        # any new scheduling).
        if resources:
            self.scheduler.force_acquire(resources, node_id)
        from ray_tpu._private import builtin_metrics
        builtin_metrics.actor_restarts().inc(tags={
            "kind": ("detached_rebind"
                     if rec.get("lifetime") == "detached" else "rebind")})
        logger.info("Rebound daemon-resident actor %s (%s) after head "
                    "restart", rec["name"] or actor_hex[:12],
                    actor_hex[:12])
        return True

    def unregister_remote_node(self, node_id: NodeID) -> None:
        with self._lock:
            self._remote_nodes.pop(node_id, None)
        # Start the staleness clock on the node's series: Prometheus
        # gets a last look, then they fall out of the exposition.
        self._cluster_metrics.mark_node_dead(node_id.hex())
        self.remove_node(node_id)

    def _remote_conn(self, spec: TaskSpec):
        node_id = getattr(spec, "_node_id", None)
        if node_id is None:
            return None
        with self._lock:
            return self._remote_nodes.get(node_id)

    def remote_node_stats(self) -> Dict[str, dict]:
        """Per-daemon counters (object-transfer bytes etc.), keyed by node
        id hex — the observability hook for the node-to-node data plane."""
        with self._lock:
            conns = dict(self._remote_nodes)
        out = {}
        for node_id, conn in conns.items():
            try:
                out[node_id.hex()] = conn.get_stats()
            except Exception:  # noqa: BLE001 - dying node mid-query
                continue
        return out

    def _result_store_limit(self, spec: TaskSpec) -> int:
        """Results above this size stay daemon-resident. Multi-return
        tasks split PER ELEMENT daemon-side (shuffle partials must ride
        the inter-daemon data plane, not the head); dynamic generators
        come back whole (item count is unknown until unpacked)."""
        if spec.num_returns == "dynamic" or spec.num_returns == 0:
            return 0
        return self._cfg_inline_limit

    def _invoke_user(self, spec: TaskSpec, fn, args, kwargs):
        """The user-code call seam: local nodes call directly (thread
        backend) or in a leased worker process; tasks placed on a remote
        daemon proxy the call over its connection (this head thread
        blocks while the daemon's CPUs do the work)."""
        conn = self._remote_conn(spec)
        if conn is None:
            if self._use_process_worker(spec):
                return self._run_in_worker_process(spec, args, kwargs)
            return fn(*args, **kwargs)
        return conn.execute_task(spec, self.functions, args, kwargs,
                                 store_limit=self._result_store_limit(spec))

    # -- process workers (reference: raylet WorkerPool) -----------------

    def _get_process_pool(self):
        # Workers get a head address so nested ray_tpu API calls bind a
        # ClientRuntime (the connected-runtime property; see
        # _private/client_runtime.py) instead of an isolated auto-init.
        # This opens the loopback head port implicitly — same trust model
        # as the reference (every ray.init binds unauthenticated local
        # ports); multi-tenant hosts share that exposure either way.
        # start_head_server is idempotent + takes the lock itself; call it
        # BEFORE taking the runtime lock here (no nested acquisition).
        head_addr = self.start_head_server()
        with self._lock:
            if self._process_pool is None:
                from ray_tpu._private.worker_process import WorkerProcessPool
                native = self.store.native
                self._process_pool = WorkerProcessPool(
                    store_name=native.name if native is not None else None,
                    head_address=head_addr)
                # Batches head-pool workers piggyback on task replies
                # merge straight into the cluster registry (the workers
                # run on the head node).
                self._process_pool.metrics_sink = self._publish_head_metrics
                self._process_pool.profile_sink = \
                    self._publish_head_profile
                self._process_pool.flow_sink = self._publish_head_flow
            return self._process_pool

    def _use_process_worker(self, spec: TaskSpec) -> bool:
        """Process isolation policy: explicit opt-in (worker_process) or
        an isolation-requiring runtime env (pip/venv). TPU tasks never
        qualify — a TPU chip is single-process and this process owns it,
        so they run on the thread backend (idiomatic for JAX: XLA
        releases the GIL during compute)."""
        renv = spec.runtime_env or {}
        if renv.get("worker_process") is False:
            return False
        if spec.resources.get("TPU", 0) > 0:
            return False
        return bool(renv.get("worker_process") or renv.get("pip")
                    or renv.get("conda"))

    def _worker_exec_msg(self, spec: TaskSpec, args, kwargs, handle,
                         mode: str = "task", method: Optional[str] = None
                         ) -> dict:
        try:
            fn_bytes = self.functions.get_bytes(spec.function_id) \
                if mode != "actor_call" else None
        except KeyError:
            raise ValueError(
                f"Task/actor {spec.name} captured objects that cannot be "
                "serialized, so it cannot run in a worker process. Make "
                "it picklable or drop worker_process from runtime_env.")
        if fn_bytes is not None and spec.function_id in handle.shipped:
            fn_bytes = None
        elif fn_bytes is not None:
            handle.shipped.add(spec.function_id)
        return {
            "type": "exec",
            "mode": mode,
            "fn_id": spec.function_id,
            "fn_bytes": fn_bytes,
            "method": method,
            "task_id": spec.task_id.hex(),
            "payload": serialization.serialize((args, kwargs)),
            "runtime_env": {k: v for k, v in (spec.runtime_env or
                                              {}).items()
                            if k not in ("worker_process",)},
            "name": spec.name,
        }

    def _run_in_worker_process(self, spec: TaskSpec, args, kwargs):
        """Run one task on a leased worker subprocess. The executor
        thread blocks on the worker socket; a SIGKILL of the worker
        (force-cancel, OOM kill) surfaces as WorkerCrashedError."""
        from ray_tpu._private.worker_process import (WorkerCrashedError,
                                                     run_on_worker)
        from ray_tpu._private.runtime_env_pip import python_for_env
        pool = self._get_process_pool()
        # pip envs run under their venv interpreter (URI-cached venv,
        # built on first use); pool reuse is keyed by interpreter.
        handle = pool.lease(python_for_env(spec.runtime_env))
        handle.current_task = spec.task_id
        with self._lock:
            self._proc_tasks[spec.task_id] = handle
        try:
            msg = self._worker_exec_msg(spec, args, kwargs, handle)
            try:
                return run_on_worker(handle, msg)
            except TaskError as te:
                from ray_tpu._private.worker_process import \
                    WorkerFnMissingError
                if not isinstance(te.cause, WorkerFnMissingError):
                    raise
                # The worker lost/never-cached the function while our
                # shipped-set said otherwise — heal by resending with
                # bytes once.
                handle.shipped.discard(spec.function_id)
                msg = self._worker_exec_msg(spec, args, kwargs, handle)
                return run_on_worker(handle, msg)
        except WorkerCrashedError:
            if getattr(spec, "_cancel_requested", False):
                raise TaskCancelledError(spec.task_id)
            raise
        finally:
            handle.current_task = None
            with self._lock:
                self._proc_tasks.pop(spec.task_id, None)
            pool.release(handle)

    def _invoke_actor_init(self, spec: TaskSpec, cls, args, kwargs):
        conn = self._remote_conn(spec)
        if conn is not None:
            from ray_tpu._private.multinode import RemoteActorInstance
            conn.create_actor(spec, self.functions, args, kwargs)
            return RemoteActorInstance(conn, spec.actor_id)
        if self._use_process_worker(spec):
            # Dedicated worker process for the actor's whole life
            # (reference: dedicated workers for actors, worker_pool.h).
            from ray_tpu._private.worker_process import (
                ProcessActorInstance, run_on_worker)
            from ray_tpu._private.runtime_env_pip import python_for_env
            pool = self._get_process_pool()
            handle = pool.lease(python_for_env(spec.runtime_env))
            handle.actor_id = spec.actor_id.hex()
            try:
                msg = self._worker_exec_msg(spec, args, kwargs, handle,
                                            mode="actor_init")
                run_on_worker(handle, msg)
            except BaseException:
                handle.kill()
                raise
            return ProcessActorInstance(handle, pool)
        return cls(*args, **kwargs)

    def _destroy_remote_instance(self, state: "ActorState") -> None:
        """Best-effort teardown of a daemon-resident or worker-process
        actor instance."""
        from ray_tpu._private.multinode import RemoteActorInstance
        from ray_tpu._private.worker_process import ProcessActorInstance
        instance = state.instance
        if isinstance(instance, RemoteActorInstance):
            instance.conn.destroy_actor(state.actor_id)
        elif isinstance(instance, ProcessActorInstance):
            instance.destroy()

    def _node_death_invalidated(self, spec: TaskSpec,
                                exc: BaseException) -> bool:
        """After a RemoteNodeDiedError, wait briefly for the connection's
        death handler to invalidate the spec (it restarts actors / retries
        tasks itself); returns whether this thread should discard its
        work. Closes the race where the send side observes the dead socket
        before the recv side has run remove_node."""
        from ray_tpu._private.multinode import RemoteNodeDiedError
        if not isinstance(exc, RemoteNodeDiedError):
            return False
        import time as _time

        from ray_tpu._private.channel import Backoff
        # Jittered backoff, not a fixed-cadence spin: the death handler
        # usually invalidates within a millisecond or two, and under a
        # mass node death dozens of waiter threads polling in lockstep
        # contend on the spec locks the handler needs.
        bo = Backoff(0.002, 0.1)
        deadline = _time.monotonic() + 5.0
        while _time.monotonic() < deadline:
            if getattr(spec, "invalidated", False):
                return True
            bo.sleep()
        return bool(getattr(spec, "invalidated", False))

    def remove_node(self, node_id: NodeID) -> None:
        """Simulate node failure: running tasks there fail (and retry
        elsewhere within budget), actors restart elsewhere (max_restarts),
        objects whose primary copy lived there are reconstructed from
        lineage (reference: NodeManager death handling + ObjectRecovery)."""
        state = self.scheduler.remove_node(node_id)
        if state is None:
            return
        self._drop_node_leases(node_id)
        # 1) In-flight tasks on the dead node. A task whose results are
        # already sealed has effectively completed — its worker thread just
        # hasn't deregistered yet; retrying it would double-execute (the
        # lost-copy case is _recover_lost_objects' job, which re-runs from
        # lineage exactly once).
        with self._lock:
            doomed = [
                s for s in self._inflight.values()
                if getattr(s, "_node_id", None) == node_id
                and s.kind != TaskKind.ACTOR_CREATION
                and not (s.return_ids and all(
                    self.store.contains(oid) for oid in s.return_ids))]
            # Mark INSIDE the lock: _store_remote_result seals results
            # under the same lock, so a completing remote task either
            # sealed before this point (→ not doomed, recovery below
            # reconstructs its daemon-resident value) or observes
            # invalidated and discards — a stale seal can never shadow
            # the retry.
            for s in doomed:
                s.invalidated = True
        for spec in doomed:
            self._try_claim_finalize(spec)
            # _retry_after_node_death releases the zombie spec's dependency
            # pins AFTER the retry clone re-pins them (releasing first could
            # free the args the retry still needs).
            self._retry_after_node_death(spec, node_id)
        # 2) Actors homed on the dead node.
        with self._lock:
            actors_snapshot = list(self._actors.values())
        dead_actors = [a for a in actors_snapshot
                       if getattr(a.creation_spec, "_node_id", None) == node_id
                       and not a.dead]
        for actor in dead_actors:
            self._handle_actor_node_death(actor, node_id)
        # 3) Lost objects → lineage reconstruction.
        self._recover_lost_objects(node_id)
        self._recover_remote_values(node_id)
        # 4) PG bundles on the dead node move to live nodes (best effort).
        self.scheduler.reschedule_lost_bundles()
        self._dispatch()

    def _retry_after_node_death(self, spec: TaskSpec, node_id: NodeID) -> None:
        err = NodeDiedError(
            f"Task {spec.name} failed: node {node_id.hex()[:12]} died while "
            "it was running.")
        if spec.attempt_number < spec.max_retries:
            # Clone: the original spec stays invalidated so its (still
            # running) zombie thread can't store results or double-release.
            retry = spec.clone_for_retry()
            with self._lock:
                for oid in retry.return_ids:
                    if oid in self._lineage:
                        self._lineage[oid] = retry
            logger.warning("Node %s died; retrying task %s (attempt %d/%d)",
                           node_id.hex()[:12], spec.name,
                           retry.attempt_number, retry.max_retries)
            # Pin the retry's deps BEFORE dropping the zombie's pins, so
            # shared argument objects never hit zero in between.
            self._register_task_refs(retry)
            self._release_task_deps(spec)
            self._resolve_dependencies(retry)
        else:
            # Seal the error directly (the spec stays invalidated so the
            # zombie thread skips its own bookkeeping). Skip objects whose
            # every handle is gone — sealing them would leak forever.
            self._release_task_deps(spec)
            for oid in spec.return_ids:
                self._store_if_referenced(oid, err, is_exception=True)
            self._record_event(spec, "FAILED")

    def _handle_actor_node_death(self, state: ActorState,
                                 node_id: NodeID) -> None:
        cause = ActorDiedError(
            state.actor_id,
            f"The actor died because its node {node_id.hex()[:12]} died.")
        can_restart = (state.max_restarts == -1
                       or state.num_restarts < state.max_restarts)
        with state.lock:
            old_executor = state.executor
            state.executor = None
            state.instance = None
            state.created.clear()
            unfinished = list(state.unfinished.values())
            state.unfinished.clear()
            state.pre_creation_queue.clear()
            if old_executor is not None:
                old_executor.stop()
            if can_restart:
                state.num_restarts += 1
                for spec in unfinished:
                    handle = spec.caller_handle_id or "default"
                    seq_state = state.seq_state.setdefault(
                        handle, {"next": 1, "waiting": {}, "aborted": set()})
                    if spec.sequence_number >= seq_state["next"]:
                        seq_state["aborted"].add(spec.sequence_number)
                for seq_state in state.seq_state.values():
                    self._drain_actor_seq(state, seq_state)
            else:
                state.dead = True
                state.death_cause = cause
                state.created.set()
        for spec in unfinished:
            self._store_error(spec, cause)
        if not can_restart:
            with self._lock:
                if state.name and not state.detached:
                    # Detached actors keep their registry entry even when
                    # the restart budget is spent: ONLY kill() removes it
                    # (get_actor still resolves; calls raise ActorDied).
                    self._named_actors.pop((state.namespace, state.name), None)
            return
        if state.name and self.gcs_store is not None:
            self.gcs_store.update_actor(state.actor_id.hex(),
                                        num_restarts=state.num_restarts)
        # Re-dispatch a CLONE of the creation task through the normal path so
        # the actor comes up on an alive node with a fresh acquisition. The
        # original spec stays invalidated: if its __init__ is still running
        # on a zombie thread, that thread discards its work.
        state.creation_spec.invalidated = True
        doomed_creation = state.creation_spec
        creation = doomed_creation.clone_for_retry()
        with state.lock:
            state.creation_spec = creation
            state.resources_released = False
        logger.warning("Node %s died; restarting actor %s elsewhere "
                       "(restart %d)", node_id.hex()[:12],
                       state.name or state.actor_id.hex()[:8],
                       state.num_restarts)
        self._register_task_refs(creation)
        self._release_task_deps(doomed_creation)
        with self._lock:
            self._ready.append(creation)

    def _recover_lost_objects(self, node_id: NodeID) -> None:
        with self._lock:
            lost = [oid for oid, nid in self._object_locations.items()
                    if nid == node_id]
            for oid in lost:
                self._object_locations.pop(oid, None)
        # The sim keeps values in the head store with a virtual location;
        # only sealed ("present") copies count as lost primaries.
        self._reconstruct_or_seal(
            lost, node_id,
            skip=lambda oid: not self.store.contains(oid))

    def _recover_remote_values(self, node_id: NodeID) -> None:
        """Daemon-resident result payloads die with their daemon: values
        the head already materialized are safe; the rest walk the
        recovery tiers — another in-memory replica holder, then a
        durable spill URI, then lineage re-execution — and only a full
        miss seals ObjectLostError."""
        with self._lock:
            lost = [(oid, k) for oid, (nid, k)
                    in self._remote_values.items() if nid == node_id]
            for oid, key in lost:
                self._remote_values.pop(oid, None)
                self._remote_keys.pop(key, None)
            # The dead daemon's cached replicas died with it.
            for reps in self._object_replicas.values():
                reps.pop(node_id, None)
        self._reconstruct_or_seal([oid for oid, _k in lost], node_id,
                                  skip=self.store.is_materialized,
                                  keys=dict(lost))

    def _recover_from_replica(self, oid: ObjectID, key: str,
                              node_id: NodeID) -> bool:
        """Tier 1: another daemon pulled a copy of this object at some
        point — if it is STILL resident there (the cache is evictable,
        so ask), re-point the head's lazy fetch at that holder: no IO,
        no re-execution (reference: object directory giving the pull
        manager its next location)."""
        from ray_tpu._private.dataplane import stat_remote
        from ray_tpu._private.multinode import RemoteValueStub
        with self._lock:
            holders = [(nid, self._remote_nodes.get(nid))
                       for nid in (self._object_replicas.get(oid) or {})
                       if nid != node_id]
        for nid, conn in holders:
            if conn is None or conn.object_addr is None:
                continue
            try:
                size = stat_remote(conn.object_addr, key, timeout=5.0)
            except (OSError, ConnectionError):
                continue
            if size < 0:
                continue  # evicted there since the pull
            stub = RemoteValueStub(conn, key, size)
            if not self.store.replace_remote_fetch(oid, stub.fetch,
                                                   size):
                return False  # entry freed/materialized meanwhile
            with self._lock:
                self._remote_values[oid] = (nid, key)
                self._remote_keys[key] = oid
            builtin_metrics.object_restores().inc(
                tags={"source": "replica"})
            self._cluster_metrics.events.record(
                "objects", f"object {oid.hex()[:12]} re-pointed at "
                f"replica holder {nid.hex()[:12]}",
                severity="info", node_id=node_id.hex(),
                labels={"tier": "replica"})
            logger.warning(
                "object %s survives node %s death on replica holder %s",
                oid.hex()[:12], node_id.hex()[:12], nid.hex()[:12])
            return True
        return False

    def _recover_from_spill(self, oid: ObjectID, key: str,
                            node_id: NodeID) -> bool:
        """Tier 2: the dead daemon had spilled this object through a
        durable backend — any node (here: the head) can read the URI
        back. Restores eagerly into the head store; the producer task
        does NOT re-run. A missing/truncated file is a tier miss."""
        with self._lock:
            rec = self._spill_uris_by_key.pop(key, None)
        if rec is None:
            return False
        uri, size = rec
        from ray_tpu._private.multinode import _loads
        from ray_tpu._private.spill import read_uri
        payload = read_uri(uri, size)
        if payload is None:
            return False  # unreadable: fall down to lineage
        try:
            value = _loads(payload)
        except Exception:  # noqa: BLE001 - corrupt payload = tier miss
            logger.exception("spilled payload %s is corrupt", uri)
            return False
        self.store.invalidate([oid])
        self.store.put_inline(oid, value)
        builtin_metrics.object_restores().inc(tags={"source": "spill"})
        self._cluster_metrics.events.record(
            "objects", f"object {oid.hex()[:12]} restored from durable "
            f"spill after node {node_id.hex()[:12]} death",
            severity="info", node_id=node_id.hex(),
            labels={"tier": "spill"})
        logger.warning(
            "restored object %s from spill URI %s after node %s death",
            oid.hex()[:12], uri, node_id.hex()[:12])
        return True

    def _restore_from_lineage(self, oid: ObjectID) -> bool:
        """ObjectStore restore-miss hook: a head-local spilled entry's
        file is gone (chaos, scrubbed tmpdir). Re-execute the creating
        task — get() re-enters and waits for the re-seal. False when no
        usable lineage exists (the store then raises ObjectLostError)."""
        with self._lock:
            spec = self._lineage.get(oid)
        if spec is None or spec.kind == TaskKind.ACTOR_TASK or \
                getattr(spec, "invalidated", False) or \
                spec.attempt_number >= spec.max_retries:
            return False
        logger.warning(
            "spilled payload of object %s is unreadable; re-executing "
            "task %s from lineage", oid.hex()[:12], spec.name)
        clone = spec.clone_for_retry()
        with self._lock:
            for roid in clone.return_ids:
                if roid in self._lineage:
                    self._lineage[roid] = clone
        self.store.invalidate(list(clone.return_ids))
        builtin_metrics.object_restores().inc(tags={"source": "lineage"})
        self._cluster_metrics.events.record(
            "objects", f"object {oid.hex()[:12]} re-executing producer "
            f"task {spec.name} from lineage (spill unreadable)",
            severity="warning", labels={"tier": "lineage"})
        self._register_task_refs(clone)
        self._resolve_dependencies(clone)
        return True

    def _reconstruct_or_seal(self, lost: List[ObjectID], node_id: NodeID,
                             skip, keys: Optional[Dict[ObjectID, str]]
                             = None) -> None:
        """Shared node-death recovery policy, cheapest tier first: an
        object with another in-memory replica holder re-points its
        fetch; one with a durable spill URI restores from disk; the
        rest re-execute their creating task from lineage (within retry
        budget) or seal ObjectLostError (reference:
        object_recovery_manager.h + local_object_manager spill URLs).
        ``keys`` maps lost oids to their daemon object keys (the handle
        the replica/spill location tables are keyed by)."""
        to_reconstruct: Dict[TaskID, TaskSpec] = {}
        plain_lost: List[ObjectID] = []
        for oid in lost:
            if skip(oid):
                continue
            key = (keys or {}).get(oid)
            if key is not None:
                if self._recover_from_replica(oid, key, node_id):
                    continue
                if self._recover_from_spill(oid, key, node_id):
                    continue
            spec = self._lineage.get(oid)
            if spec is None or spec.kind == TaskKind.ACTOR_TASK or \
                    getattr(spec, "invalidated", False) or \
                    spec.attempt_number >= spec.max_retries:
                # No lineage (e.g. ray.put or actor-task result), or the
                # retry budget is spent: unrecoverable (reference seals
                # ObjectReconstructionFailedError in this case).
                plain_lost.append(oid)
            else:
                to_reconstruct[spec.task_id] = spec
                builtin_metrics.object_restores().inc(
                    tags={"source": "lineage"})
        invalidate = [oid for spec in to_reconstruct.values()
                      for oid in spec.return_ids]
        self.store.invalidate(invalidate)
        for oid in plain_lost:
            self.store.invalidate([oid])
            self.store.put_inline(oid, ObjectLostError(
                f"Object {oid.hex()} was on node {node_id.hex()[:12]} which "
                "died, and it cannot be reconstructed (no task lineage, or "
                "the task's retry budget is exhausted)."),
                is_exception=True)
        for spec in to_reconstruct.values():
            logger.warning("Reconstructing objects of task %s after node %s "
                           "death", spec.name, node_id.hex()[:12])
            clone = spec.clone_for_retry()
            with self._lock:
                for oid in clone.return_ids:
                    if oid in self._lineage:
                        self._lineage[oid] = clone
            self._register_task_refs(clone)
            self._resolve_dependencies(clone)

    # ------------------------------------------------------------------
    # Introspection / lifecycle
    # ------------------------------------------------------------------

    def _record_event(self, spec: TaskSpec, status: str) -> None:
        # Node attribution: set at _try_launch (None for pre-placement
        # SUBMITTED events) — feeds the per-node rate series and the
        # state API's node_id column.
        nid = getattr(spec, "_node_id", None)
        node_hex = nid.hex() if nid is not None else None
        builtin_metrics.record_task_event(status, node_hex)
        if len(self._task_events) < self._cfg_max_task_events:
            self._task_events.append({
                "task_id": spec.task_id.hex(),
                "name": spec.name,
                "status": status,
                "node_id": node_hex,
                "time": time.time(),
            })
        # State transitions fan out on the pubsub hub (reference:
        # TaskEventBuffer flush → GcsTaskManager → subscribers).
        self.pubsub.publish("task_events", spec.task_id.hex(), status)

    def task_events(self) -> List[dict]:
        return list(self._task_events)

    def pending_resource_demand(self) -> List[Dict[str, float]]:
        """Resource shapes of queued-but-unschedulable tasks (the analog of
        the reference's backlog/demand report feeding autoscaler
        LoadMetrics)."""
        with self._lock:
            return [dict(s.resources) for s in self._ready_specs_locked()
                    if s.resources]

    def cluster_resources(self) -> Dict[str, float]:
        return dict(self.scheduler.total)

    def available_resources(self) -> Dict[str, float]:
        return dict(self.scheduler.available)

    def shutdown(self) -> None:
        from ray_tpu.exceptions import RayError

        # Log subsystem first: the monitor's final drain still has a
        # live pubsub, and the printer flushes what's already queued.
        # clear_session() detaches the process globals so later spawns
        # in this process don't write into a dead session's directory
        # (the files themselves stay for `ray-tpu logs`).
        from ray_tpu._private import ray_logging
        if self._metrics_agent is not None:
            # No drain: the only sink is this runtime's own registry.
            self._metrics_agent.stop(drain=False)
            self._metrics_agent = None
        if self._log_monitor is not None:
            self._log_monitor.stop()
            self._log_monitor = None
        if self._log_printer is not None:
            self._log_printer.stop()
            self._log_printer = None
        ray_logging.clear_session()
        if self.gcs_store is not None:
            rec = self.gcs_store.jobs.get(self._gcs_job_key)
            if rec is not None:
                rec = dict(rec, status="FINISHED",
                           end_time=time.time())
                self.gcs_store.record_job(self._gcs_job_key, rec)
            # Land any throttled object-directory writes before exit.
            try:
                self.gcs_store.flush()
            except OSError:
                pass
        # Detached actors survive an orderly shutdown (reference: GCS-
        # owned lifetime): their host daemons are closed WITHOUT the
        # shutdown frame — the daemon treats it as connection loss,
        # keeps the resident instance, and a later head on the same
        # port + gcs_store_path rebinds it. Non-detached named actors
        # are reaped for real: registry record removed (no rebind after
        # an orderly exit) and resident instances on surviving daemons
        # destroyed.
        with self._lock:
            remote_nodes = dict(self._remote_nodes)
            actors = list(self._actors.values())
        detached_nodes = set()
        for state in actors:
            node_id = getattr(state.creation_spec, "_node_id", None)
            if state.detached and not state.dead \
                    and node_id in remote_nodes:
                detached_nodes.add(node_id)
        for state in actors:
            if state.detached and not state.dead:
                continue
            node_id = getattr(state.creation_spec, "_node_id", None)
            if state.name and self.gcs_store is not None:
                self.gcs_store.remove_actor(state.actor_id.hex())
            if node_id in detached_nodes and not state.dead:
                # This daemon outlives the driver; don't leave a zombie
                # resident instance it would re-announce on reconnect.
                try:
                    remote_nodes[node_id].destroy_actor(state.actor_id)
                except Exception:  # noqa: BLE001 - best effort
                    pass
        if self._head_server is not None:
            self._head_server.stop(keep_nodes=detached_nodes)
            self._head_server = None
        with self._lock:
            self._remote_nodes.clear()
            self._shutdown = True
            workers = list(self._all_workers)
            actors = list(self._actors.values())
        for state in actors:
            if state.executor is not None:
                state.executor.stop()
            state.dead = True
            state.created.set()
        for w in workers:
            w.stop()
        if self._process_pool is not None:
            self._process_pool.shutdown()
        if self.memory_monitor is not None:
            self.memory_monitor.stop()
        # Pooled data-plane sockets + owner borrow channels die with the
        # runtime — idle keep-alive connections to (possibly dead)
        # peers must not outlive it as CLOSE_WAIT fds.
        from ray_tpu._private import dataplane as _dp
        _dp.GLOBAL_PEER_CONNS.close()
        # The GC thread must be fully stopped BEFORE the native store is
        # closed: a free() racing close() would touch an unmapped arena
        # (segfault). Wake it, let it observe _shutdown, and join.
        self._gc_event.set()
        self._gc_thread.join(timeout=5)
        # Wake every blocked get with an error rather than hanging.
        self.store.fail_all_pending(
            RayError("The runtime was shut down while this object was "
                     "still pending."))
        if self.store.native is not None:
            if self._gc_thread.is_alive():
                # Better to leak the arena than unmap it under a live
                # free() (the join timed out — should not happen).
                logger.warning("GC thread still alive at shutdown; "
                               "leaving the native arena mapped")
            else:
                self.store.native.close()
