"""Process-based worker pool: real OS worker processes for task/actor
execution.

The analog of the reference's worker pool + per-process core-worker
execution loop (src/ray/raylet/worker_pool.h:156 PopWorker;
src/ray/core_worker/core_worker.cc:2377 ExecuteTask in a separate
process). Both the head runtime and node daemons lease workers from a
:class:`WorkerProcessPool`; each worker is a subprocess speaking the
framed cloudpickle protocol over an inherited socketpair.

What processes buy (and threads cannot):

* **real force-cancel / kill** — SIGKILL the worker, the task genuinely
  stops (reference: worker process kill on ``ray.cancel(force=True)``);
* **real OOM kill** — the victim's RSS is returned to the OS
  (reference: raylet worker_killing_policy);
* **crash isolation** — a segfaulting C extension takes down one worker,
  not the node.

Data path: arguments whose payload lives in the node's shm arena travel
as :class:`ArenaRef`/:class:`ArenaArrayRef` markers; the worker attaches
the arena by name (shm_store.cc metadata lives in the mapping, so any
process on the host shares the store) and reads zero-copy —
``jax.device_put`` on such a view is the host->TPU path with no copy.

TPU policy: workers are spawned WITHOUT the TPU backend environment
(a TPU chip is single-process; the chip-owning process — driver or
daemon — runs TPU tasks on threads, everything else can isolate).
"""

from __future__ import annotations

import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import traceback
import uuid
from typing import Any, Callable, Dict, Optional

from ray_tpu._private import builtin_metrics, procinfo, ray_logging

logger = logging.getLogger(__name__)


class WorkerCrashedError(RuntimeError):
    """The worker process died mid-task (crash, kill, or OOM kill)."""


class WorkerFnMissingError(RuntimeError):
    """The worker does not have the function cached and the parent
    withheld the bytes. The parent heals by resending WITH bytes (covers
    any path where a prior request marked the fn shipped but the worker
    failed before caching it)."""


class ArenaRef:
    """Marker for a serialized payload resident in the host shm arena."""

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key


class ArenaArrayRef:
    """Marker for a numpy array resident in the host shm arena (stored
    with put_array's header). Resolves to a READ-ONLY zero-copy view."""

    __slots__ = ("key",)

    def __init__(self, key: str):
        self.key = key


# ---------------------------------------------------------------------------
# Parent side
# ---------------------------------------------------------------------------


class _StdioTransport:
    """Socket-shaped transport over a child's stdin/stdout pipes — the
    CONTAINER transport: ``docker run -i`` cannot inherit a socketpair
    fd across the container boundary, but stdio crosses it natively
    (reference: _private/runtime_env/container.py wraps workers in
    podman; the control channel must survive the wrap)."""

    def __init__(self, proc: subprocess.Popen):
        self._proc = proc

    def sendall(self, data: bytes) -> None:
        self._proc.stdin.write(data)
        self._proc.stdin.flush()

    def recv(self, n: int) -> bytes:
        return self._proc.stdout.read1(n)

    def settimeout(self, timeout) -> None:
        pass  # pipes signal worker death via EOF, not timeouts

    def close(self) -> None:
        for stream in (self._proc.stdin, self._proc.stdout):
            try:
                stream.close()
            except Exception:  # noqa: BLE001
                pass


def container_engine() -> Optional[str]:
    """The available container engine binary (podman preferred, like the
    reference), or None. RAY_TPU_CONTAINER_ENGINE overrides detection."""
    import shutil
    forced = os.environ.get("RAY_TPU_CONTAINER_ENGINE")
    if forced:
        return forced if shutil.which(forced) else None
    for engine in ("podman", "docker"):
        if shutil.which(engine):
            return engine
    return None


class WorkerHandle:
    """One leased worker subprocess. At most one request in flight (the
    reference's workers are also one-task-at-a-time)."""

    def __init__(self, proc: subprocess.Popen, sock: socket.socket):
        self.proc = proc
        self.sock = sock
        self.pid = proc.pid
        self.dead = False
        self.actor_id: Optional[str] = None  # dedicated actor worker
        self.current_task: Optional[Any] = None  # task_id while executing
        self.shipped: set = set()  # fn_ids this worker has cached
        # Workers can't push unsolicited frames (strict request/reply),
        # so their metrics agent buffers batches that piggyback on task
        # replies; the pool points this at the host's forwarder.
        self.metrics_sink: Optional[Callable[[dict], Any]] = None
        # Same piggyback for continuous-profiling windows (folded
        # stacks accumulated by the worker's ProfilerAgent).
        self.profile_sink: Optional[Callable[[dict], Any]] = None
        # And for the worker's transfer-ledger drains (FlowRecorder).
        self.flow_sink: Optional[Callable[[dict], Any]] = None
        self._lock = threading.Lock()

    def request(self, msg: dict, timeout: Optional[float] = None) -> dict:
        """Send one request and block for its reply. A dead/killed worker
        raises WorkerCrashedError."""
        from ray_tpu._private.multinode import (_dumps, _loads, _recv_frame,
                                                _send_frame)
        with self._lock:
            if self.dead:
                raise WorkerCrashedError(
                    f"worker {self.pid} is already dead")
            try:
                self.sock.settimeout(timeout)
                _send_frame(self.sock, _dumps(msg))
                reply = _loads(_recv_frame(self.sock))
            except (OSError, ConnectionError, EOFError) as exc:
                self.dead = True
                raise WorkerCrashedError(
                    f"worker {self.pid} died mid-request "
                    f"(exit={self.proc.poll()}): {exc}") from exc
            finally:
                try:
                    self.sock.settimeout(None)
                except OSError:
                    pass
        if isinstance(reply, dict):
            batches = reply.pop("metrics_batch", None)
            sink = self.metrics_sink
            if batches and sink is not None:
                for batch in batches:
                    try:
                        sink(batch)
                    except Exception:  # noqa: BLE001 - metrics never fail a task
                        logger.exception("worker metrics forward failed")
            profiles = reply.pop("profile_batch", None)
            psink = self.profile_sink
            if profiles and psink is not None:
                for batch in profiles:
                    try:
                        psink(batch)
                    except Exception:  # noqa: BLE001 - profiling never fails a task
                        logger.exception("worker profile forward failed")
            flows = reply.pop("flow_batch", None)
            fsink = self.flow_sink
            if flows and fsink is not None:
                for batch in flows:
                    try:
                        fsink(batch)
                    except Exception:  # noqa: BLE001 - flow accounting never fails a task
                        logger.exception("worker flow forward failed")
        return reply

    def kill(self, wait: bool = True) -> None:
        """SIGKILL the worker — the real force-cancel/OOM-kill path; its
        RSS is returned to the OS. ``wait=False`` skips the reap (for
        callers holding locks; the pool's poll() reaps later)."""
        self.dead = True
        try:
            os.kill(self.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        if wait:
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        try:
            self.sock.close()
        except OSError:
            pass

    def stop(self) -> None:
        """Graceful shutdown (idle workers at pool teardown)."""
        from ray_tpu._private.multinode import (_dumps,
                                                _send_frame_best_effort)
        self.dead = True
        _send_frame_best_effort(self.sock, _dumps({"type": "exit"}))
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.kill()
        try:
            self.sock.close()
        except OSError:
            pass
        cidfile = getattr(self, "cidfile", None)
        if cidfile is not None:  # containerized: clean exit reaps the cid
            try:
                os.unlink(cidfile)
            except OSError:
                pass


def _spawn_worker(store_name: Optional[str],
                  env_overrides: Optional[Dict[str, str]] = None,
                  python_exe: Optional[str] = None,
                  container: Optional[Dict[str, Any]] = None
                  ) -> WorkerHandle:
    env = dict(os.environ)
    # No TPU backend in workers: the chip is single-process (owned by the
    # spawning driver/daemon), and skipping the accelerator site hook
    # makes spawns ~6x faster. Workers that import jax get CPU.
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("JAX_PLATFORMS", None)
    env["RAY_TPU_WORKER"] = "1"
    if env_overrides:
        env.update(env_overrides)
    if container:
        return _spawn_container_worker(store_name, env, container)
    parent_sock, child_sock = socket.socketpair()
    cmd = [python_exe or sys.executable, "-m",
           "ray_tpu._private.worker_process",
           "--fd", str(child_sock.fileno())]
    if store_name:
        cmd += ["--store", store_name]

    def _die_with_parent():
        # PR_SET_PDEATHSIG: if the spawning driver/daemon dies (even
        # SIGKILL), the kernel reaps the worker too — no orphaned workers
        # burning CPU after a node death.
        try:
            import ctypes
            ctypes.CDLL("libc.so.6", use_errno=True).prctl(
                1, signal.SIGKILL, 0, 0, 0)
        except Exception:  # noqa: BLE001 - non-Linux: best effort
            pass

    # Capture stdout/stderr to per-proc session files (the log monitor
    # streams them to the driver); without a session the child simply
    # inherits the parent's streams — output is never swallowed.
    capture = ray_logging.open_worker_capture()
    popen_kwargs: Dict[str, Any] = {}
    if capture is not None:
        env["PYTHONUNBUFFERED"] = "1"  # print() must reach the tailer
        env[ray_logging.MARKER_ENV] = "1"
        popen_kwargs["stdout"] = capture.out
        popen_kwargs["stderr"] = capture.err
    try:
        proc = subprocess.Popen(cmd, env=env,
                                pass_fds=[child_sock.fileno()],
                                preexec_fn=_die_with_parent,
                                **popen_kwargs)
    except BaseException:
        if capture is not None:
            capture.abort()
        raise
    if capture is not None:
        capture.finalize(proc.pid)
    child_sock.close()
    return WorkerHandle(proc, parent_sock)


def _spawn_container_worker(store_name: Optional[str],
                            env: Dict[str, str],
                            container: Dict[str, Any]) -> WorkerHandle:
    """Spawn the worker INSIDE a container (reference:
    _private/runtime_env/container.py): the engine runs the worker image
    with /dev/shm shared (the object arena crosses the boundary as a
    named shm mapping) and the framed protocol rides stdio."""
    engine = container_engine()
    if engine is None:
        raise WorkerCrashedError(
            "runtime_env['container'] needs docker or podman on PATH")
    image = container.get("image")
    if not image:
        raise WorkerCrashedError(
            "runtime_env['container'] must set 'image'")
    # PDEATHSIG below only kills the ENGINE CLIENT process; under docker
    # the container itself runs under containerd and would outlive a
    # crashed daemon despite --rm. --cidfile gives the daemon (or the
    # next daemon on this host) a handle to reap strays; --init makes
    # in-container signal handling sane (zombie-reaping PID 1).
    cid_dir = os.path.join(tempfile.gettempdir(), "ray_tpu_containers")
    os.makedirs(cid_dir, exist_ok=True)
    _reap_stale_containers_once(engine, cid_dir)
    token = procinfo.start_token(os.getpid())
    cidfile = os.path.join(
        cid_dir,
        f"{os.getpid()}.{token if token is not None else ''}"
        f"-{uuid.uuid4().hex}.cid")
    cmd = [engine, "run", "--rm", "-i", "--init", "--network=host",
           "--cidfile", cidfile,
           "-v", "/dev/shm:/dev/shm"]
    # Only stderr is capturable here: stdout is the protocol pipe (the
    # worker's --stdio mode points fd 1 at stderr before user code, so
    # print() output lands in the captured .err).
    capture = ray_logging.open_worker_capture(sources=("err",))
    if capture is not None:
        env[ray_logging.MARKER_ENV] = "1"
    for key in ("RAY_TPU_WORKER", "RAY_TPU_HEAD_ADDRESS",
                ray_logging.MARKER_ENV):
        if env.get(key):
            cmd += ["-e", f"{key}={env[key]}"]
    cmd += list(container.get("run_options") or [])
    cmd += [image, container.get("python", "python"), "-m",
            "ray_tpu._private.worker_process", "--stdio"]
    if store_name:
        cmd += ["--store", store_name]

    def _die_with_parent():
        try:
            import ctypes
            ctypes.CDLL("libc.so.6", use_errno=True).prctl(
                1, signal.SIGKILL, 0, 0, 0)
        except Exception:  # noqa: BLE001 - non-Linux: best effort
            pass

    popen_kwargs: Dict[str, Any] = {}
    if capture is not None:
        popen_kwargs["stderr"] = capture.err
    try:
        proc = subprocess.Popen(cmd, env=env, stdin=subprocess.PIPE,
                                stdout=subprocess.PIPE,
                                preexec_fn=_die_with_parent,
                                **popen_kwargs)
    except BaseException:
        if capture is not None:
            capture.abort()
        raise
    if capture is not None:
        capture.finalize(proc.pid)
    handle = WorkerHandle(proc, _StdioTransport(proc))
    handle.cidfile = cidfile
    return handle


_reaped = threading.Event()


def _reap_stale_containers_once(engine: str, cid_dir: str) -> None:
    """Housekeeping, off the spawn hot path: the first container lease
    in this process kicks one background reap (each stale cid costs a
    `docker rm -f` of up to 30s — never serialized into a dispatch)."""
    if _reaped.is_set():
        return
    _reaped.set()
    threading.Thread(target=_reap_stale_containers,
                     args=(engine, cid_dir),
                     name="ray_tpu-container-reaper", daemon=True).start()


def _reap_stale_containers(engine: str, cid_dir: str) -> None:
    """Kill containers whose spawning daemon died (its pid is gone but
    the cidfile remains): the PDEATHSIG on the engine client cannot stop
    a containerd-managed container."""
    try:
        entries = os.listdir(cid_dir)
    except OSError:
        return
    for fname in entries:
        if not fname.endswith(".cid"):
            continue
        path = os.path.join(cid_dir, fname)
        try:
            ident = fname.split("-", 1)[0]
            # "<pid>.<start_token>" since r5; bare "<pid>" from older
            # daemons. The token defeats pid recycling: an unrelated
            # live process that inherited the pid must not keep an
            # orphaned container alive forever.
            spawner_token = None
            if "." in ident:
                pid_s, tok_s = ident.split(".", 1)
                spawner_pid = int(pid_s)
                spawner_token = int(tok_s) if tok_s else None
            else:
                spawner_pid = int(ident)
            if procinfo.same_process(spawner_pid, spawner_token):
                continue  # spawner alive: its container is legitimate
            with open(path) as f:
                cid = f.read().strip()
            if cid:
                subprocess.run([engine, "rm", "-f", cid],
                               capture_output=True, timeout=30)
            os.unlink(path)
        except (OSError, ValueError, subprocess.SubprocessError):
            continue


class WorkerProcessPool:
    """Leases worker subprocesses, reusing idle ones (reference:
    WorkerPool caches started workers keyed by runtime-env hash;
    PopWorker reuses before starting). Idle workers are keyed by their
    interpreter (base vs. a pip-venv python): a venv task never reuses a
    base worker and vice versa. Dedicated (actor) workers never return
    to the idle pool."""

    def __init__(self, store_name: Optional[str] = None,
                 max_workers: int = 64,
                 head_address=None, node_id_hex: Optional[str] = None,
                 object_addr=None):
        self.store_name = store_name
        self.max_workers = max_workers
        # Workers inherit the head address so nested ray_tpu API calls in
        # user code bind a ClientRuntime wired to the head (the connected-
        # runtime property; _private/client_runtime.py) instead of
        # auto-initializing an isolated split-brain runtime. The node id
        # lets worker-side puts register THIS node as the bytes' owner
        # (distributed ownership; stale after a head restart, in which
        # case registration fails and puts fall back to head-stored).
        self._env_overrides: Optional[Dict[str, str]] = None
        overrides = {}
        if head_address is not None:
            host, port = tuple(head_address)
            overrides["RAY_TPU_HEAD_ADDRESS"] = f"{host}:{port}"
        if node_id_hex:
            overrides["RAY_TPU_NODE_ID"] = node_id_hex
        if object_addr is not None:
            # This node's object server: worker-side puts stamp it into
            # owner hints so borrowers can go owner-ward (phase 3).
            host, port = tuple(object_addr)
            overrides["RAY_TPU_OBJECT_ADDR"] = f"{host}:{port}"
        if overrides:
            self._env_overrides = overrides
        self._idle: Dict[str, list] = {}
        self._all: list = []
        self._lock = threading.Lock()
        self._available = threading.Condition(self._lock)
        self._closed = False
        # Forwarder handed to every leased worker: batches the workers
        # piggyback on task replies flow through here to the head's
        # cluster registry (directly on the head; via metrics_batch
        # frames from a daemon).
        self.metrics_sink: Optional[Callable[[dict], Any]] = None
        self.profile_sink: Optional[Callable[[dict], Any]] = None
        self.flow_sink: Optional[Callable[[dict], Any]] = None
        # ALL spawns go through this single long-lived thread:
        # PR_SET_PDEATHSIG binds to the spawning THREAD, so a worker
        # forked from an ephemeral handler thread is SIGKILLed the
        # moment that thread exits (the daemon runs one thread per
        # request — its first worker died right after its first task).
        # The spawner lives until pool shutdown; its death then reaps
        # every worker, which is exactly the orphan protection wanted.
        import concurrent.futures
        self._spawner = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ray_tpu-worker-spawn")

    def lease(self, python_exe: Optional[str] = None,
              container: Optional[Dict[str, Any]] = None) -> WorkerHandle:
        """Lease a worker for the given interpreter (None = base) or
        container image, spawning up to max_workers total; BLOCKS when
        the pool is saturated until a worker is released (backpressure,
        not failure — callers already queued behind the scheduler).
        Idle workers are keyed by interpreter AND image: a containerized
        worker never serves a bare task or another image's."""
        key = python_exe or ""
        if container:
            key += f"|container:{container.get('image')}"
        # The common case — an idle worker is parked — must not pay two
        # monotonic reads plus a locked histogram observe per lease: the
        # clock starts only once the request actually waits, spawns, or
        # evicts; an immediate hit records a plain int add.
        lease_start: Optional[float] = None
        while True:
            evict = None
            with self._lock:
                while True:
                    idle = self._idle.setdefault(key, [])
                    while idle:
                        w = idle.pop()
                        if not w.dead and w.proc.poll() is None:
                            return self._leased(w, lease_start)
                        # Died while parked: without this, it counts
                        # toward max_workers forever (capacity leak).
                        w.dead = True
                        if w in self._all:
                            self._all.remove(w)
                    if self._closed:
                        raise WorkerCrashedError("worker pool is shut down")
                    if lease_start is None:
                        lease_start = time.monotonic()
                    if len([w for w in self._all if not w.dead]) \
                            < self.max_workers:
                        break
                    # At capacity: evict an idle worker of ANOTHER
                    # interpreter key to make room — otherwise a pool
                    # full of idle base workers deadlocks the first
                    # venv lease (reference: WorkerPool kills idle
                    # workers of other runtime envs under pressure).
                    for other, lst in self._idle.items():
                        if other != key and lst:
                            evict = lst.pop()
                            if evict in self._all:
                                self._all.remove(evict)
                            break
                    if evict is not None:
                        break
                    self._available.wait(timeout=10)
            if evict is not None:
                evict.stop()
                evict = None
                continue  # re-enter: capacity freed
            w = self._spawner.submit(
                _spawn_worker, self.store_name,
                env_overrides=self._env_overrides,
                python_exe=python_exe, container=container).result()
            w.pool_key = key
            with self._lock:
                if self._closed:
                    pass  # fall through; stop below
                else:
                    self._all.append(w)
                    return self._leased(w, lease_start)
            w.stop()
            raise WorkerCrashedError("worker pool is shut down")

    def _leased(self, w: WorkerHandle,
                lease_start: Optional[float]) -> WorkerHandle:
        w.metrics_sink = self.metrics_sink
        w.profile_sink = self.profile_sink
        w.flow_sink = self.flow_sink
        if lease_start is None:
            builtin_metrics.record_lease_immediate()
        else:
            builtin_metrics.worker_lease_wait().observe(
                time.monotonic() - lease_start)
        return w

    def record_metrics(self) -> None:
        """Refresh the pool-size gauge (metrics-agent collector)."""
        with self._lock:
            alive = len([w for w in self._all if not w.dead])
        builtin_metrics.worker_pool_size().set(alive)

    def prestart(self, n: int) -> None:
        """Spawn up to ``n`` base-interpreter workers into the idle pool
        ahead of demand (reference: worker_pool.h PrestartWorkers): the
        Popen returns immediately and the child warms up concurrently,
        so the first real task pays a queue pop instead of a process
        start."""
        def one():
            try:
                self.release(self.lease(None))
            except Exception:  # noqa: BLE001 - prestart is best-effort
                pass

        for _ in range(max(0, n)):
            threading.Thread(target=one, daemon=True,
                             name="ray_tpu-worker-prestart").start()

    def release(self, w: WorkerHandle) -> None:
        if w.dead:
            # Reap killed workers here (the force-cancel/OOM path kills
            # with wait=False while holding the runtime lock): without
            # the wait() the SIGKILLed process lingers as a zombie.
            try:
                w.proc.wait(timeout=10)
            except Exception:  # noqa: BLE001 - already reaped / stuck
                pass
        with self._lock:
            if not w.dead and not self._closed and w.actor_id is None:
                self._idle.setdefault(
                    getattr(w, "pool_key", ""), []).append(w)
            self._available.notify()

    def running_workers(self) -> list:
        with self._lock:
            return [w for w in self._all
                    if not w.dead and w.current_task is not None]

    def shutdown(self) -> None:
        with self._lock:
            self._closed = True
            workers = list(self._all)
            self._all.clear()
            self._idle.clear()
        for w in workers:
            if not w.dead:
                w.stop()
        # Last: the spawner thread's death PDEATHSIG-kills any worker
        # that somehow escaped the stop() sweep above.
        self._spawner.shutdown(wait=False)


# ---------------------------------------------------------------------------
# Shared request/response helpers (parent side)
# ---------------------------------------------------------------------------


def run_on_worker(handle: WorkerHandle, msg: dict):
    """Execute one request on a worker; unpack the reply into a value or
    raise. Worker death surfaces as WorkerCrashedError (a SYSTEM failure:
    retriable, like a died worker process in the reference)."""
    from ray_tpu._private.multinode import _loads
    reply = handle.request(msg)
    if reply.get("ok"):
        return _loads(reply["value"])
    exc, remote_tb = _loads(reply["error"])
    from ray_tpu.exceptions import TaskError
    raise TaskError(exc, remote_tb, msg.get("name", "task"))


class ProcessActorInstance:
    """Placeholder stored as ActorState.instance for actors living in a
    dedicated worker process; method lookups return proxy closures
    (mirrors multinode.RemoteActorInstance for daemon-resident actors)."""

    def __init__(self, handle: WorkerHandle, pool: WorkerProcessPool):
        self.handle = handle
        self.pool = pool

    def bind_method(self, method_name: str, task_name: str,
                    store_limit: int = 0):
        from ray_tpu._private import serialization

        def call(*args, **kwargs):
            # Runs inside the head-side actor_task:: span
            # (_run_actor_task's continue_context): propagate it so the
            # worker-process span parents across the process boundary.
            from ray_tpu.util import tracing
            return run_on_worker(self.handle, {
                "type": "exec",
                "mode": "actor_call",
                "method": method_name,
                "payload": serialization.serialize((args, kwargs)),
                "name": task_name,
                "trace_ctx": tracing.span_context(tracing.current_span()),
            })
        return call

    def destroy(self) -> None:
        self.handle.kill()


# ---------------------------------------------------------------------------
# Worker side (subprocess entrypoint)
# ---------------------------------------------------------------------------


#: The _WorkerMain serving THIS worker process (None elsewhere): lets
#: the client runtime reach the shared shm arena for node-resident puts
#: (distributed ownership — client_runtime._put_node_resident).
_current_executor: Optional["_WorkerMain"] = None


class _WorkerMain:
    def __init__(self, sock: socket.socket, store_name: Optional[str]):
        global _current_executor
        _current_executor = self
        self.sock = sock
        self.store_name = store_name
        self._arena = None
        self._arena_tried = False
        self._functions: Dict[bytes, Any] = {}
        self._actor = None  # dedicated actor instance
        # Metrics export rides task replies (workers cannot push
        # unsolicited frames): the agent runs with no thread, serve()
        # polls it at most once per interval and attaches buffered
        # batches to the next reply; the parent forwards them head-ward.
        from ray_tpu._private.metrics_agent import MetricsAgent
        self._metrics_buffer: list = []
        self._profile_buffer: list = []
        self._flow_buffer: list = []
        # publish_profile makes the agent own a ProfilerAgent for this
        # worker: sampling runs continuously on its own thread even
        # between tasks; the windows ride task replies like metrics.
        self._metrics_agent = MetricsAgent(
            self._buffer_metrics_batch, component="worker", start=False,
            publish_profile=self._buffer_profile_batch,
            publish_flow=self._buffer_flow_batch)
        self._last_metrics_poll = 0.0

    def _buffer_metrics_batch(self, batch: dict) -> bool:
        self._metrics_buffer.append(batch)
        # Bounded: an idle stretch can't pile up batches (the periodic
        # full refresh re-converges the head after any drop).
        del self._metrics_buffer[:-8]
        return True

    def _buffer_profile_batch(self, batch: dict) -> bool:
        # Bounded like metrics — but a squeezed-out window would be
        # real sample loss, so a full buffer REFUSES the batch instead:
        # the agent refunds the stacks into the live window and they
        # merge into the next drain.
        if len(self._profile_buffer) >= 8:
            return False
        self._profile_buffer.append(batch)
        return True

    def _buffer_flow_batch(self, batch: dict) -> bool:
        # A squeezed-out batch would be dropped transfer records, so a
        # full buffer REFUSES (the agent refunds into the recorder).
        if len(self._flow_buffer) >= 8:
            return False
        self._flow_buffer.append(batch)
        return True

    def _attach_metrics(self, reply: dict) -> None:
        agent = self._metrics_agent
        if not agent.enabled:
            return
        now = time.monotonic()
        if now - self._last_metrics_poll >= agent.interval_s:
            self._last_metrics_poll = now
            try:
                agent.poll_once()
            except Exception:  # noqa: BLE001 - metrics never fail a task
                logger.exception("worker metrics poll failed")
        if self._metrics_buffer:
            reply["metrics_batch"] = self._metrics_buffer[:]
            del self._metrics_buffer[:]
        if self._profile_buffer:
            reply["profile_batch"] = self._profile_buffer[:]
            del self._profile_buffer[:]
        if self._flow_buffer:
            reply["flow_batch"] = self._flow_buffer[:]
            del self._flow_buffer[:]

    def _get_arena(self):
        if not self._arena_tried:
            self._arena_tried = True
            if self.store_name:
                try:
                    from ray_tpu._private.native_store import \
                        NativeObjectStore
                    self._arena = NativeObjectStore(name=self.store_name,
                                                    create=False)
                except Exception:  # noqa: BLE001 - arena gone/unbuildable
                    logger.exception("worker could not attach shm arena")
        return self._arena

    def _load_function(self, fn_id: bytes, fn_bytes: Optional[bytes]):
        fn = self._functions.get(fn_id)
        if fn is None:
            if fn_bytes is None:
                raise WorkerFnMissingError(
                    "worker has no cached copy of this function; parent "
                    "must resend with fn_bytes")
            from ray_tpu._private import serialization
            fn = serialization.loads_function(fn_bytes)
            self._functions[fn_id] = fn
        return fn

    def _resolve(self, obj, pinned_keys):
        """Resolve arena markers to values (zero-copy views for arrays).
        A missing entry means it was evicted between the parent's check
        and this read — an ObjectPullError, so the head retries the task
        as a system failure while reconstruction re-runs the producer."""
        from ray_tpu._private.dataplane import ObjectPullError
        if isinstance(obj, ArenaArrayRef):
            arena = self._get_arena()
            if arena is None:
                raise RuntimeError("shm arena unavailable in worker")
            arr = arena.get_array(obj.key)
            if arr is None:
                raise ObjectPullError(
                    f"array {obj.key} no longer in the shm arena "
                    "(evicted under pressure before the worker's read)")
            # get_array pinned the entry; release after the task body so
            # repeated tasks never pin objects forever.
            pinned_keys.append(obj.key)
            return arr  # READ-ONLY zero-copy view over the mapping
        if isinstance(obj, ArenaRef):
            arena = self._get_arena()
            if arena is None:
                raise RuntimeError("shm arena unavailable in worker")
            view = arena.get_bytes(obj.key)
            if view is None:
                raise ObjectPullError(
                    f"object {obj.key} no longer in the shm arena "
                    "(evicted under pressure before the worker's read)")
            from ray_tpu._private.multinode import _loads
            try:
                return _loads(view)
            finally:
                view.release()
                arena.release(obj.key)
        return obj

    def _exec(self, msg: dict):
        from ray_tpu._private.multinode import _loads
        mode = msg.get("mode", "task")
        # Load the function FIRST: once cached, a later arg failure
        # cannot leave the parent's shipped-set out of sync.
        if mode == "actor_call":
            if self._actor is None:
                raise RuntimeError("actor_call before actor_init")
            fn = getattr(self._actor, msg["method"])
        else:
            fn = self._load_function(msg["fn_id"], msg.get("fn_bytes"))
        # Task context: get_tpu_ids / nested client-runtime gets read it
        # (a blocked nested get ships task_id so the head can release the
        # task's resources while it waits).
        import types as _types

        from ray_tpu._private.runtime import _task_context
        _task_context.spec = _types.SimpleNamespace(
            _tpu_ids=None, actor_id=None, name=msg.get("name", ""),
            task_id_hex=msg.get("task_id"))
        if ray_logging.markers_enabled():
            # Announce the task on the captured streams so the tailer
            # prefixes its output with the task name, not just the pid.
            ray_logging.emit_task_marker(msg.get("name", ""))
        pinned_keys: list = []
        try:
            args, kwargs = _loads(msg["payload"])
            args = [self._resolve(a, pinned_keys) for a in args]
            kwargs = {k: self._resolve(v, pinned_keys)
                      for k, v in kwargs.items()}
            renv = msg.get("runtime_env")

            def invoke():
                # Final hop of cross-process propagation: the span ships
                # back piggybacked on this reply's metrics_batch. ctx is
                # None on every untraced task (one dict read).
                from ray_tpu.util import tracing
                prefix = ("actor_task" if mode == "actor_call" else
                          "actor_init" if mode == "actor_init" else
                          "task")
                with tracing.continue_context(
                        msg.get("trace_ctx"),
                        f"{prefix}::{msg.get('name', '')}",
                        {"stage": "execute"}):
                    result = fn(*args, **kwargs)
                    import inspect
                    if inspect.iscoroutine(result):
                        import asyncio
                        result = asyncio.run(result)
                return result

            if renv:
                from ray_tpu._private import runtime_env as _renv
                _renv.setup(renv)
                if mode == "actor_init":
                    # A dedicated actor worker IS the actor's process:
                    # its env_vars persist for the process lifetime
                    # (reference actor runtime_env semantics), so
                    # threads the actor spawns (e.g. Train loops
                    # reading RAY_TPU_JAX_PLATFORM) and later method
                    # calls all see them — the scoped form here lost a
                    # race that deadlocked multi-controller training.
                    import os as _os
                    _os.environ.update(renv.get("env_vars") or {})
                    result = invoke()
                else:
                    with _renv.applied(renv):
                        result = invoke()
            else:
                result = invoke()
        finally:
            _task_context.spec = None
            arena = self._arena
            for key in pinned_keys:
                try:
                    arena.release(key)
                except Exception:  # noqa: BLE001
                    pass
        if mode == "actor_init":
            self._actor = result
            return None
        return result

    def _result_reply(self, msg: dict, value, _dumps) -> dict:
        """Build the result reply, writing big payloads STRAIGHT into
        the shared shm arena (plasma's mission: results land in the
        store, never in an RPC reply) — the bytes skip the stdio pipe
        and the daemon's re-pickle; it only adopts the keys. Multi-
        return tasks split PER ELEMENT (a shuffle map's 32 partitions
        each become an independent arena entry). Arena-full or shape
        mismatch falls back to the inline path (the daemon's table.put
        can spill to disk)."""
        from ray_tpu._private import serialization
        arena_limit = msg.get("arena_limit", 0)
        num_returns = msg.get("num_returns", 1)
        arena = self._get_arena() if arena_limit else None
        if arena is None:
            return {"ok": True, "value": _dumps(value)}
        import uuid as _uuid

        def _one(el) -> dict:
            # serialize_parts keeps big array buffers as raw views: an
            # arena-bound result is laid down header+buffers in one
            # allocation with a single data memcpy (no full-payload
            # pickle copy on this end).
            pp = serialization.serialize_parts(el)
            size = sum(len(p) for p in pp)
            if size > arena_limit:
                key = f"wres-{_uuid.uuid4().hex}"
                if arena.put_parts(key, pp, size=size):
                    return {"arena_key": key, "size": size}
            if len(pp) == 1 and isinstance(pp[0], bytes):
                return {"value": pp[0]}
            return {"value": b"".join(bytes(p) for p in pp)}

        if num_returns > 1:
            if not isinstance(value, (tuple, list)) or \
                    len(value) != num_returns:
                # Wrong shape: the daemon's mismatch path describes it.
                return {"ok": True, "value": _dumps(value)}
            return {"ok": True, "parts": [_one(el) for el in value]}
        reply = _one(value)
        reply["ok"] = True
        return reply

    def serve(self) -> None:
        from ray_tpu._private.multinode import (_dumps, _loads, _recv_frame,
                                                _send_frame)
        while True:
            try:
                msg = _loads(_recv_frame(self.sock))
            except (ConnectionError, OSError):
                return  # parent died — exit with it
            kind = msg.get("type")
            if kind == "exit":
                return
            if kind == "ping":
                reply = {"ok": True, "pid": os.getpid()}
                self._attach_metrics(reply)
                _send_frame(self.sock, _dumps(reply))
                continue
            if kind == "profile":
                # On-demand burst relayed by the owning daemon
                # (`ray-tpu profile --pid`): sample our own stacks at
                # the requested rate and reply with the raw folded
                # mapping. Strict request/reply holds: this occupies
                # the pipe for the duration, like any task would.
                try:
                    from ray_tpu._private.profiling import sample_self
                    # skip_profiler=False: a worker may be just this
                    # serve thread — skipping the sampling thread would
                    # return an EMPTY profile for any idle worker.
                    counts = sample_self(
                        min(float(msg.get("duration", 5.0)), 60.0),
                        int(msg.get("hz", 100)), skip_profiler=False)
                    reply = {"ok": True, "pid": os.getpid(),
                             "stacks": counts}
                except BaseException as exc:  # noqa: BLE001 - ship to parent
                    reply = {"ok": False,
                             "error": f"{type(exc).__name__}: {exc}"}
                try:
                    _send_frame(self.sock, _dumps(reply))
                except (OSError, ConnectionError):
                    return
                continue
            try:
                value = self._exec(msg)
                reply = self._result_reply(msg, value, _dumps)
            except BaseException as exc:  # noqa: BLE001 - ship to parent
                try:
                    payload = _dumps((exc, traceback.format_exc()))
                except Exception:  # noqa: BLE001 - unpicklable exception
                    payload = _dumps((RuntimeError(
                        f"{type(exc).__name__}: {exc}"),
                        traceback.format_exc()))
                reply = {"ok": False, "error": payload}
            self._attach_metrics(reply)
            try:
                _send_frame(self.sock, _dumps(reply))
            except (OSError, ConnectionError):
                return


def _main() -> None:
    import argparse
    import faulthandler

    # Stack dumps on demand: `kill -USR1 <worker>` prints every thread
    # to stderr (inherited from the spawning process) — the diagnostic
    # channel for wedged workers, mirroring the reference's py-spy-based
    # dashboard stack dumps.
    faulthandler.enable()
    try:
        faulthandler.register(signal.SIGUSR1)
    except (AttributeError, ValueError):  # non-main thread / platform
        pass

    # Worker processes NEVER run TPU tasks (the chip is single-process;
    # runtime._uses_worker_process and the daemon's routing both keep
    # TPU work in the chip-owning process) — but site hooks that preload
    # jax would otherwise initialize the TPU backend here and DEADLOCK
    # on the chip's lockfile (/tmp/libtpu_lockfile) against the owning
    # process. If a hook DID preload jax (it is in sys.modules despite
    # the spawn env scrub), pin the platform in-process before any
    # device use. Otherwise do NOT import jax here — that costs seconds
    # on every worker spawn — and let the env pin cover a later lazy
    # import by user code.
    if "jax" in sys.modules:
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except Exception:  # noqa: BLE001 - nothing to pin
            pass
    else:
        # Hard assignment, not setdefault: a site hook that re-exported
        # JAX_PLATFORMS (without importing jax) must not win — user code
        # importing jax later gets CPU, never the daemon-owned chip.
        os.environ["JAX_PLATFORMS"] = "cpu"

    parser = argparse.ArgumentParser()
    parser.add_argument("--fd", type=int, default=None)
    parser.add_argument("--stdio", action="store_true",
                        help="speak the framed protocol over stdio "
                             "(container transport: fds cannot cross "
                             "the container boundary)")
    parser.add_argument("--store", default=None)
    args = parser.parse_args()
    if args.stdio:
        # Claim the REAL stdout for frames, then point fd 1 at stderr so
        # user-code prints can never corrupt the protocol stream.
        real_out = os.fdopen(os.dup(1), "wb", buffering=0)
        real_in = os.fdopen(os.dup(0), "rb", buffering=0)
        os.dup2(2, 1)

        class _StdioServer:
            def recv(self, n):
                return real_in.read(n) or b""

            def sendall(self, data):
                real_out.write(data)

            def settimeout(self, timeout):
                pass

            def close(self):
                pass

        _WorkerMain(_StdioServer(), args.store).serve()
        return
    if args.fd is None:
        parser.error("one of --fd or --stdio is required")
    sock = socket.socket(fileno=args.fd)
    _WorkerMain(sock, args.store).serve()


if __name__ == "__main__":
    from ray_tpu._private.worker_process import _main as _canonical_main

    _canonical_main()
