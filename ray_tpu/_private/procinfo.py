"""Portable process-liveness probes.

Housekeeping paths (spill-dir reaping, container reaping) must decide
whether some *other* process is alive. ``/proc/<pid>`` existence is
Linux-only — on macOS/BSD every pid looks dead, which would rmtree a
live daemon's spill directory. ``kill(pid, 0)`` is POSIX-portable.

Pid reuse is the second hazard: a recycled pid makes an orphan look
alive forever. ``start_token`` captures the process start time (Linux
``/proc/<pid>/stat`` field 22, in clock ticks since boot) so a
(pid, token) pair uniquely names one process incarnation. Where the
token is unavailable the callers degrade to liveness-only.

Reference: ray uses pid+start-time pairs for the same reason in its
worker-liveness checks (src/ray/util/process.h).
"""
from __future__ import annotations

import os
from typing import Optional


def pid_alive(pid: int) -> bool:
    """True if a process with this pid exists (portable: signal 0)."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False
    return True


def start_token(pid: int) -> Optional[int]:
    """Start-time token for pid-recycling detection; None if unknown.

    Field 22 of /proc/<pid>/stat is counted after the final ')' because
    the comm field (2) may itself contain spaces or parentheses.
    """
    try:
        with open(f"/proc/{pid}/stat", "rb") as f:
            data = f.read()
        rest = data.rsplit(b")", 1)[1].split()
        return int(rest[19])
    except (OSError, ValueError, IndexError):
        return None


def same_process(pid: int, token: Optional[int]) -> bool:
    """True iff pid is alive AND (when a token is known for both sides)
    it is the same incarnation that minted the token."""
    if not pid_alive(pid):
        return False
    if token is None:
        return True  # no token recorded: liveness is all we can check
    current = start_token(pid)
    if current is None:
        return True  # no /proc here: cannot disprove, assume same
    return current == token
