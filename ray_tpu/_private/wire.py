"""Typed wire schema for the head↔daemon control channel (phase 1).

Analog of the reference's proto contract (src/ray/protobuf/
node_manager.proto:352 + core_worker.proto): every control message has a
declared type with a field schema, and peers perform a PROTOCOL VERSION
handshake at registration — a daemon from a different release is
rejected with a clear error instead of failing later with an opaque
unpickling or KeyError deep inside a handler. Pickle remains the
ENVELOPE (this runtime's frames are cloudpickle dicts) and user
payloads stay opaque bytes; what this module adds is the versioned,
validated CONTRACT for the control fields around them.

Raising the version: bump PROTOCOL_VERSION whenever a message type is
added/removed or a field changes meaning. Additive OPTIONAL fields may
keep the version (old peers ignore unknown fields; validation here
accepts extras for exactly that reason).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

#: Bump on any incompatible control-channel change (see module doc).
#: v2: task_batch / reply_batch coalesced frames (either peer may emit
#: them, so a v1 peer would fail on an unknown type).
PROTOCOL_VERSION = 2


class WireSchemaError(ValueError):
    """A control message does not match its declared schema."""


_ANY = object()  # payload fields: opaque, any type
_STR = (str,)
_INT = (int,)
_NUM = (int, float)
_BOOL = (bool,)
_BYTES = (bytes,)
_DICT = (dict,)
_LIST = (list, tuple)
_OPT_STR = (str, type(None))
_OPT_BYTES = (bytes, type(None))

#: type name -> {field: (allowed types | _ANY, required)}. Extra fields
#: are ALLOWED (additive evolution); wrong types and missing required
#: fields are not.
SCHEMAS: Dict[str, Dict[str, Tuple[Any, bool]]] = {
    # -- session establishment -----------------------------------------
    "register": {
        "protocol": (_INT, True),
        "resources": (_DICT, True),
        "labels": ((dict, type(None)), False),
        "object_addr": (_LIST, False),
        "store_name": (_OPT_STR, False),
        "resident_actors": (_LIST, False),
    },
    "registered": {"node_id": (_STR, True)},
    "register_rejected": {"error": (_STR, True),
                          "head_protocol": (_INT, True)},
    "health_channel": {"node_id": (_STR, True)},
    "client_runtime": {},  # fields owned by client_runtime.py
    "client_registered": {"job_id": (_STR, True),
                          "session_id": (_STR, True)},
    # -- task / actor execution (head -> daemon) -----------------------
    "execute_task": {
        "req_id": (_INT, True),
        "fn_id": (_BYTES, True),
        "fn_bytes": (_OPT_BYTES, False),
        "payload": (_BYTES, True),   # pickled user args: opaque
        "name": (_STR, False),
        "task_id": (_STR, False),
        "runtime_env": ((dict, type(None)), False),
        "tpu_ids": ((list, tuple, type(None)), False),
        "num_cpus": (_NUM, False),
        "store_limit": (_INT, False),
        "num_returns": (_INT, False),
        "lease_id": (_STR, False),
        "plain_args": (_BOOL, False),
        "class_id": (_STR, False),
    },
    "create_actor": {
        "req_id": (_INT, True),
        "actor_id": (_STR, True),
        "fn_id": (_BYTES, True),
        "fn_bytes": (_OPT_BYTES, False),
        "payload": (_BYTES, True),
        "name": (_STR, False),
        "task_id": (_STR, False),
        "runtime_env": ((dict, type(None)), False),
        "tpu_ids": ((list, tuple, type(None)), False),
    },
    "actor_call": {
        "req_id": (_INT, True),
        "actor_id": (_STR, True),
        "method": (_STR, True),
        "payload": (_BYTES, True),
        "name": (_STR, False),
        "store_limit": (_INT, False),
        "num_returns": (_INT, False),
    },
    "destroy_actor": {"actor_id": (_STR, True)},
    # -- object plane (head -> daemon) ---------------------------------
    "fetch_object": {"req_id": (_INT, True), "key": (_STR, True)},
    "free_object": {"key": (_STR, True)},
    "adopt_object": {"req_id": (_INT, True), "key": (_STR, True),
                     "size": (_INT, True)},
    # -- leases / control ----------------------------------------------
    "drop_lease": {"lease_id": (_STR, True)},
    "reclaim_tasks": {"class_id": (_STR, True), "max_n": (_INT, True)},
    "spill_lease": {"lease_id": (_STR, True)},
    "unspill_lease": {"lease_id": (_STR, True)},
    "stats": {"req_id": (_INT, True)},
    "profile": {"req_id": (_INT, True), "duration": (_NUM, False),
                "hz": (_INT, False), "fmt": (_STR, False)},
    "shutdown": {},
    # -- frame coalescing (both directions, v2) ------------------------
    # A batch frame wraps N control messages that accumulated at the
    # sender while the socket was busy (one pickle + one syscall for
    # all of them). Inner messages are validated individually by the
    # receiver; reply_batch carries type-less reply frames.
    "task_batch": {"msgs": (_LIST, True)},
    "reply_batch": {"msgs": (_LIST, True)},
    # -- liveness ------------------------------------------------------
    "ping": {"cluster_digest": ((dict, type(None)), False)},
    "pong": {"sync": (_ANY, False)},
    # -- internal completion marker (never crosses the wire) -----------
    "died": {},
}


def validate_message(msg: Dict[str, Any]) -> None:
    """Validate one control message against its type's schema. Raises
    WireSchemaError naming the exact field. Reply frames (req_id +
    ok/value/error, no "type") are validated by shape separately."""
    mtype = msg.get("type")
    if mtype is None:
        # Reply frame: {"req_id": int, "ok": bool, ...}.
        if "req_id" not in msg:
            raise WireSchemaError(
                f"frame has neither type nor req_id: {sorted(msg)}")
        if not isinstance(msg["req_id"], int):
            raise WireSchemaError("reply req_id must be int")
        return
    spec = SCHEMAS.get(mtype)
    if spec is None:
        raise WireSchemaError(
            f"unknown control message type {mtype!r} (peer from another "
            f"protocol version? this side speaks v{PROTOCOL_VERSION})")
    _validate_fields(spec, msg, str(mtype))


def _validate_fields(spec, msg, label: str) -> None:
    """One rule set for BOTH channels — required fields, type checks,
    extras allowed (additive evolution)."""
    for field, (types, required) in spec.items():
        if field not in msg:
            if required:
                raise WireSchemaError(
                    f"{label}: missing required field {field!r}")
            continue
        if types is _ANY:
            continue
        value = msg[field]
        if not isinstance(value, types):
            raise WireSchemaError(
                f"{label}: field {field!r} must be "
                f"{'/'.join(t.__name__ for t in types)}, got "
                f"{type(value).__name__}")


#: Client-channel op schemas (the ClientRuntime <-> ClientSession
#: surface): op name -> {field: (types, required)}. Validated server-
#: side before dispatch — a drifted client op fails with the exact
#: field name, not a KeyError inside a handler. Extra fields allowed
#: (additive evolution), user payloads stay opaque bytes.
CLIENT_SCHEMAS: Dict[str, Dict[str, Tuple[Any, bool]]] = {
    "submit_task": {"spec": (_BYTES, True)},
    "submit_actor_task": {"spec": (_BYTES, True)},
    "create_actor": {"spec": (_BYTES, True), "opts": (_DICT, True)},
    "actor_info": {"actor_id": (_STR, True)},
    "get_named_actor": {"name": (_STR, True), "namespace": (_STR, True)},
    "kill_actor": {"actor_id": (_STR, True), "no_restart": (_BOOL, True)},
    "cancel": {"ref": (_STR, True), "force": (_BOOL, True)},
    "reg_fn": {"payload": (_BYTES, True)},
    "fn_bytes": {"fn_id": (_BYTES, True)},
    "put": {"payload": (_BYTES, True)},
    "put_remote": {"node": (_STR, True), "key": (_STR, True),
                   "size": (_INT, True), "adopt": (_BOOL, False)},
    "get": {"refs": (_LIST, True),
            "timeout": ((int, float, type(None)), False),
            "holding_task": (_OPT_STR, False)},
    "wait": {"refs": (_LIST, True), "num_returns": (_INT, True),
             "timeout": ((int, float, type(None)), False)},
    "contains": {"ref": (_STR, True)},
    "free": {"refs": (_LIST, True)},
    "cluster_resources": {},
    "available_resources": {},
    "nodes": {},
    "pg_exists": {"pg_id": (_STR, True)},
    "create_pg": {"bundles": (_LIST, True), "strategy": (_STR, True),
                  "name": (_STR, True)},
    "remove_pg": {"pg_id": (_STR, True)},
    "task_events": {},
    "kv_put": {"ns": (_ANY, True), "key": (_ANY, True),
               "value": (_ANY, True), "overwrite": (_BOOL, True)},
    "kv_get": {"ns": (_ANY, True), "key": (_ANY, True)},
    "kv_del": {"ns": (_ANY, True), "key": (_ANY, True)},
    "kv_keys": {"ns": (_ANY, True), "prefix": (_ANY, False)},
    "ping": {},
    "ref_add": {"ref": (_STR, True)},
    "ref_del": {"ref": (_STR, True)},
}


def validate_client_op(msg: Dict[str, Any]) -> None:
    """Validate one client-channel request against its op's schema."""
    op = msg.get("op")
    spec = CLIENT_SCHEMAS.get(op)
    if spec is None:
        raise WireSchemaError(
            f"unknown client op {op!r} (peer from another protocol "
            f"version? this side speaks v{PROTOCOL_VERSION})")
    _validate_fields(spec, msg, f"client op {op}")


class ProtocolMismatch(ConnectionError):
    """Peer speaks a different control-protocol version."""


def check_peer_protocol(peer_version, peer_desc: str) -> None:
    """Raise ProtocolMismatch with a clear, actionable error when the
    peer's handshake version differs (reference: the GRPC contract is
    compiled in; here the handshake carries it explicitly)."""
    if peer_version != PROTOCOL_VERSION:
        raise ProtocolMismatch(
            f"{peer_desc} speaks control protocol "
            f"v{peer_version if peer_version is not None else '<pre-1>'} "
            f"but this process speaks v{PROTOCOL_VERSION}; upgrade the "
            "older side — mixed-version clusters are not supported")
