"""Typed wire schema for the head↔daemon control channel (phase 1).

Analog of the reference's proto contract (src/ray/protobuf/
node_manager.proto:352 + core_worker.proto): every control message has a
declared type with a field schema, and peers perform a PROTOCOL VERSION
handshake at registration — a daemon from a different release is
rejected with a clear error instead of failing later with an opaque
unpickling or KeyError deep inside a handler. Pickle remains the
ENVELOPE (this runtime's frames are cloudpickle dicts) and user
payloads stay opaque bytes; what this module adds is the versioned,
validated CONTRACT for the control fields around them.

Raising the version: bump PROTOCOL_VERSION whenever a message type is
added/removed or a field changes meaning. Additive OPTIONAL fields may
keep the version (old peers ignore unknown fields; validation here
accepts extras for exactly that reason).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

#: Bump on any incompatible control-channel change (see module doc).
#: v2: task_batch / reply_batch coalesced frames (either peer may emit
#: them, so a v1 peer would fail on an unknown type).
#: v3: typed binary layouts for the hot ops (execute_task, value/
#: stored/error replies, fetch_object) + binary batch frames — frames
#: are discriminated by leading magic byte (0x01 typed, 0x02 batch,
#: 0x80 cloudpickle envelope).
#: v4: log_batch frames (daemon -> head log streaming) — a v3 head
#: would reject the unknown type in validate_message.
#: v5: metrics_batch frames (worker/daemon -> head metrics + span
#: export) — a v4 head would reject the unknown type.
#: v6: data-plane ranged-read op — the object server accepts
#: "@{offset}:{length}:{key}" requests so pullers fetch large objects
#: as parallel chunks. Encoded as an ordinary key lookup, so a v5
#: server replies -1 (unknown key) with framing intact and a v6 puller
#: degrades to the whole-object fetch; control schemas are unchanged.
#: v7: resilient session channels — post-handshake frames are wrapped
#: in a seq envelope (0x03 magic: sequence number + cumulative ack)
#: and held in a resend ring until acked; a broken channel is re-dialed
#: and resumed via the raw resume/resumed handshake instead of
#: declaring the node dead. A v6 peer would neither envelope its frames
#: nor understand the resume message, so the version must not match.
#: v8: object_spilled / object_unspilled frames (daemon -> head durable
#: spill-location announcements feeding tiered object recovery) — a v7
#: head would reject the unknown type in validate_message.
#: v9: fenced membership — the seq envelope grows a u32 node_epoch
#: field (a v8 peer would misparse every enveloped frame), the
#: registered ack and the resume handshake carry the incarnation epoch,
#: and a new raw ``fenced`` reply rejects resumes from declared-dead
#: incarnations (the daemon must re-register as a new incarnation).
#: (still v9) additive since: metrics_batch.event_stats,
#: profile_batch push frames, profile.pid burst targeting,
#: flow_batch push frames (dataplane transfer ledger) — optional
#: fields / head-bound pushes old peers drop harmlessly, per the rule
#: above; push_object frames (collective-dataplane tree broadcast:
#: head->daemon directives an old daemon answers with "unknown message
#: type", which the head's broadcast treats as a per-node miss, never a
#: session failure) and the "~<ms>:<key>" blocking-wait object-server
#: op (an ordinary key to an old server: instant -1, the waiter
#: degrades to client-side polling).
PROTOCOL_VERSION = 9


class WireSchemaError(ValueError):
    """A control message does not match its declared schema."""


_ANY = object()  # payload fields: opaque, any type
_STR = (str,)
_INT = (int,)
_NUM = (int, float)
_BOOL = (bool,)
_BYTES = (bytes,)
_DICT = (dict,)
_LIST = (list, tuple)
_OPT_STR = (str, type(None))
_OPT_BYTES = (bytes, type(None))

#: type name -> {field: (allowed types | _ANY, required)}. Extra fields
#: are ALLOWED (additive evolution); wrong types and missing required
#: fields are not.
SCHEMAS: Dict[str, Dict[str, Tuple[Any, bool]]] = {
    # -- session establishment -----------------------------------------
    "register": {
        "protocol": (_INT, True),
        "resources": (_DICT, True),
        "labels": ((dict, type(None)), False),
        "object_addr": (_LIST, False),
        "store_name": (_OPT_STR, False),
        "resident_actors": (_LIST, False),
        # The daemon's previous incarnation epoch (0 = first join): a
        # returning daemon whose old epoch was fenced must not have its
        # stale resident actors rebound (they were declared dead when
        # the lease expired — rebinding would resurrect zombies).
        "prev_epoch": (_INT, False),
    },
    "registered": {"node_id": (_STR, True),
                   "channel_token": (_OPT_STR, False),
                   "node_epoch": (_INT, False)},
    "register_rejected": {"error": (_STR, True),
                          "head_protocol": (_INT, True)},
    # -- channel resume (raw, un-enveloped handshake frames; v7) -------
    "resume": {
        "protocol": (_INT, True),
        "node_id": (_STR, True),
        "token": (_STR, True),
        "last_seq": (_INT, True),
        "epoch": (_INT, False),
    },
    "resumed": {"last_seq": (_INT, True)},
    "resume_rejected": {"error": (_STR, True)},
    # A resume (or frame) from a declared-dead incarnation: the daemon
    # must drop its session state and re-register as a NEW incarnation
    # (v9 membership fencing — distinct from resume_rejected so the
    # daemon knows its resident actors were already declared dead).
    "fenced": {"error": (_STR, True), "epoch": (_INT, False)},
    "health_channel": {"node_id": (_STR, True)},
    "client_runtime": {},  # fields owned by client_runtime.py
    "client_registered": {"job_id": (_STR, True),
                          "session_id": (_STR, True)},
    # -- task / actor execution (head -> daemon) -----------------------
    "execute_task": {
        "req_id": (_INT, True),
        "fn_id": (_BYTES, True),
        "fn_bytes": (_OPT_BYTES, False),
        "payload": (_BYTES, True),   # pickled user args: opaque
        "name": (_STR, False),
        "task_id": (_STR, False),
        "runtime_env": ((dict, type(None)), False),
        "tpu_ids": ((list, tuple, type(None)), False),
        "num_cpus": (_NUM, False),
        "store_limit": (_INT, False),
        "num_returns": (_INT, False),
        "lease_id": (_STR, False),
        "plain_args": (_BOOL, False),
        "class_id": (_STR, False),
    },
    "create_actor": {
        "req_id": (_INT, True),
        "actor_id": (_STR, True),
        "fn_id": (_BYTES, True),
        "fn_bytes": (_OPT_BYTES, False),
        "payload": (_BYTES, True),
        "name": (_STR, False),
        "task_id": (_STR, False),
        "runtime_env": ((dict, type(None)), False),
        "tpu_ids": ((list, tuple, type(None)), False),
    },
    "actor_call": {
        "req_id": (_INT, True),
        "actor_id": (_STR, True),
        "method": (_STR, True),
        "payload": (_BYTES, True),
        "name": (_STR, False),
        "store_limit": (_INT, False),
        "num_returns": (_INT, False),
    },
    "destroy_actor": {"actor_id": (_STR, True)},
    # -- object plane (head -> daemon) ---------------------------------
    "fetch_object": {"req_id": (_INT, True), "key": (_STR, True)},
    "free_object": {"key": (_STR, True)},
    "adopt_object": {"req_id": (_INT, True), "key": (_STR, True),
                     "size": (_INT, True)},
    # Tree-broadcast directive (additive post-v9): replicate ``key``
    # onto this daemon. Either ``data`` carries the payload inline (the
    # head seeding its direct children — head egress is fanout x size,
    # not N x size) or the daemon blocking-waits on ``parent`` (an
    # object-server [host, port]) until the parent's copy lands, then
    # pulls — ``alts`` (grandparent/root servers) are the re-parenting
    # failover path when an interior tree node dies mid-broadcast. The
    # reply (bytes/failovers) is the completion notice that streams
    # replica-table updates back as nodes finish.
    "push_object": {
        "req_id": (_INT, True),
        "key": (_STR, True),
        "size": (_INT, True),
        "data": (_OPT_BYTES, False),
        "parent": ((list, tuple, type(None)), False),
        "alts": (_LIST, False),
        "wait_timeout_s": (_NUM, False),
    },
    # -- leases / control ----------------------------------------------
    "drop_lease": {"lease_id": (_STR, True)},
    "reclaim_tasks": {"class_id": (_STR, True), "max_n": (_INT, True)},
    "spill_lease": {"lease_id": (_STR, True)},
    "unspill_lease": {"lease_id": (_STR, True)},
    "stats": {"req_id": (_INT, True)},
    # ``pid`` (additive, post-v9) retargets the burst at one of the
    # daemon's pool workers (cooperative sampling over the worker pipe);
    # absent/0 samples the daemon itself. fmt "dict" returns the raw
    # folded-count mapping for head-side merging (cluster bursts).
    "profile": {"req_id": (_INT, True), "duration": (_NUM, False),
                "hz": (_INT, False), "fmt": (_STR, False),
                "pid": (_INT, False)},
    "shutdown": {},
    # -- frame coalescing (both directions, v2) ------------------------
    # A batch frame wraps N control messages that accumulated at the
    # sender while the socket was busy (one pickle + one syscall for
    # all of them). Inner messages are validated individually by the
    # receiver; reply_batch carries type-less reply frames.
    "task_batch": {"msgs": (_LIST, True)},
    "reply_batch": {"msgs": (_LIST, True)},
    # -- log streaming (daemon -> head, v4) ----------------------------
    # Batched tail output from a node's LogMonitor; the head fans it
    # out to driver subscribers over pubsub. node_id is stamped by the
    # daemon; task_name comes from stream markers and may be absent.
    "log_batch": {
        "node_id": (_STR, False),
        "pid": (_INT, True),
        "proc_name": (_STR, True),
        "source": (_STR, True),
        "task_name": (_OPT_STR, False),
        "lines": (_LIST, True),
    },
    # -- metrics export (daemon -> head, v5) ---------------------------
    # One process's registry snapshot diff (util/metrics.py snapshot
    # entries — cumulative values, merged by overwrite at the head) plus
    # any tracing spans that ended since the last frame. node_id is
    # stamped by the daemon; component tells head/daemon/worker apart.
    "metrics_batch": {
        "node_id": (_STR, False),
        "pid": (_INT, True),
        "component": (_STR, True),
        "metrics": (_LIST, True),
        "spans": (_LIST, False),
        # Additive (post-v9): the publishing process's EventStats
        # summary ({handler: count/run/queue percentiles}) — daemons
        # piggyback control-loop visibility on the frames they already
        # send; older peers simply omit it.
        "event_stats": (_DICT, False),
    },
    # -- continuous profiling (daemon -> head, additive post-v9) -------
    # Folded stacks the origin's ProfilerAgent accumulated since its
    # last metrics tick ("thread [state];outer;...;inner" -> count),
    # shipped on the metrics cadence exactly like metrics_batch. Safe
    # without a version bump: daemon->head pushes are routed by type in
    # the head's recv loop, and an older head silently drops unknown
    # push frames (no req_id -> no pending waiter), losing only the
    # feature, never the session.
    "profile_batch": {
        "node_id": (_STR, False),
        "pid": (_INT, True),
        "component": (_STR, True),
        "stacks": (_DICT, True),
        "samples": (_INT, False),
        "duration_s": (_NUM, False),
    },
    # -- dataplane flow ledger (daemon -> head, additive post-v9) ------
    # Typed per-transfer records ({key, bytes, src, dst, duration,
    # chunks, parallelism, failovers, tier, direction, outcome}) the
    # origin's FlowRecorder accumulated since its last metrics tick,
    # shipped on the metrics cadence exactly like profile_batch. Same
    # compatibility story: an older head drops the unknown push type.
    "flow_batch": {
        "node_id": (_STR, False),
        "pid": (_INT, True),
        "component": (_STR, True),
        "records": (_LIST, True),
    },
    # -- durable spill announcements (daemon -> head, v8) --------------
    # A daemon spilled an object through a DURABLE backend (session://
    # or a remote store): the URI joins the head's location table so
    # node death restores from disk instead of re-executing lineage.
    # object_unspilled retracts it (restore-promotion or free).
    "object_spilled": {
        "key": (_STR, True),
        "uri": (_STR, True),
        "size": (_INT, True),
    },
    "object_unspilled": {"key": (_STR, True)},
    # -- liveness ------------------------------------------------------
    "ping": {"cluster_digest": ((dict, type(None)), False)},
    "pong": {"sync": (_ANY, False)},
    # -- internal completion marker (never crosses the wire) -----------
    "died": {},
}


def validate_message(msg: Dict[str, Any]) -> None:
    """Validate one control message against its type's schema. Raises
    WireSchemaError naming the exact field. Reply frames (req_id +
    ok/value/error, no "type") are validated by shape separately."""
    mtype = msg.get("type")
    if mtype is None:
        # Reply frame: {"req_id": int, "ok": bool, ...}.
        if "req_id" not in msg:
            raise WireSchemaError(
                f"frame has neither type nor req_id: {sorted(msg)}")
        if not isinstance(msg["req_id"], int):
            raise WireSchemaError("reply req_id must be int")
        return
    spec = SCHEMAS.get(mtype)
    if spec is None:
        raise WireSchemaError(
            f"unknown control message type {mtype!r} (peer from another "
            f"protocol version? this side speaks v{PROTOCOL_VERSION})")
    _validate_fields(spec, msg, str(mtype))


def _validate_fields(spec, msg, label: str) -> None:
    """One rule set for BOTH channels — required fields, type checks,
    extras allowed (additive evolution)."""
    for field, (types, required) in spec.items():
        if field not in msg:
            if required:
                raise WireSchemaError(
                    f"{label}: missing required field {field!r}")
            continue
        if types is _ANY:
            continue
        value = msg[field]
        if not isinstance(value, types):
            raise WireSchemaError(
                f"{label}: field {field!r} must be "
                f"{'/'.join(t.__name__ for t in types)}, got "
                f"{type(value).__name__}")


#: Client-channel op schemas (the ClientRuntime <-> ClientSession
#: surface): op name -> {field: (types, required)}. Validated server-
#: side before dispatch — a drifted client op fails with the exact
#: field name, not a KeyError inside a handler. Extra fields allowed
#: (additive evolution), user payloads stay opaque bytes.
CLIENT_SCHEMAS: Dict[str, Dict[str, Tuple[Any, bool]]] = {
    "submit_task": {"spec": (_BYTES, True)},
    "submit_actor_task": {"spec": (_BYTES, True)},
    "create_actor": {"spec": (_BYTES, True), "opts": (_DICT, True)},
    "actor_info": {"actor_id": (_STR, True)},
    "get_named_actor": {"name": (_STR, True), "namespace": (_STR, True)},
    "kill_actor": {"actor_id": (_STR, True), "no_restart": (_BOOL, True)},
    "cancel": {"ref": (_STR, True), "force": (_BOOL, True)},
    "reg_fn": {"payload": (_BYTES, True)},
    "fn_bytes": {"fn_id": (_BYTES, True)},
    "put": {"payload": (_BYTES, True)},
    "put_remote": {"node": (_STR, True), "key": (_STR, True),
                   "size": (_INT, True), "adopt": (_BOOL, False)},
    "get": {"refs": (_LIST, True),
            "timeout": ((int, float, type(None)), False),
            "holding_task": (_OPT_STR, False)},
    "wait": {"refs": (_LIST, True), "num_returns": (_INT, True),
             "timeout": ((int, float, type(None)), False)},
    "contains": {"ref": (_STR, True)},
    "free": {"refs": (_LIST, True)},
    "cluster_resources": {},
    "available_resources": {},
    "nodes": {},
    "pg_exists": {"pg_id": (_STR, True)},
    "create_pg": {"bundles": (_LIST, True), "strategy": (_STR, True),
                  "name": (_STR, True)},
    "remove_pg": {"pg_id": (_STR, True)},
    "task_events": {},
    "kv_put": {"ns": (_ANY, True), "key": (_ANY, True),
               "value": (_ANY, True), "overwrite": (_BOOL, True)},
    "kv_get": {"ns": (_ANY, True), "key": (_ANY, True)},
    "kv_del": {"ns": (_ANY, True), "key": (_ANY, True)},
    "kv_keys": {"ns": (_ANY, True), "prefix": (_ANY, False)},
    "ping": {},
    "ref_add": {"ref": (_STR, True)},
    "ref_del": {"ref": (_STR, True)},
}


def validate_client_op(msg: Dict[str, Any]) -> None:
    """Validate one client-channel request against its op's schema."""
    op = msg.get("op")
    spec = CLIENT_SCHEMAS.get(op)
    if spec is None:
        raise WireSchemaError(
            f"unknown client op {op!r} (peer from another protocol "
            f"version? this side speaks v{PROTOCOL_VERSION})")
    _validate_fields(spec, msg, f"client op {op}")


class ProtocolMismatch(ConnectionError):
    """Peer speaks a different control-protocol version."""


def check_peer_protocol(peer_version, peer_desc: str) -> None:
    """Raise ProtocolMismatch with a clear, actionable error when the
    peer's handshake version differs (reference: the GRPC contract is
    compiled in; here the handshake carries it explicitly)."""
    if peer_version != PROTOCOL_VERSION:
        raise ProtocolMismatch(
            f"{peer_desc} speaks control protocol "
            f"v{peer_version if peer_version is not None else '<pre-1>'} "
            f"but this process speaks v{PROTOCOL_VERSION}; upgrade the "
            "older side — mixed-version clusters are not supported")


# ---------------------------------------------------------------------------
# Phase-2 typed BINARY encodings for the hot-path ops (reference: the
# proto contract compiles task/result messages to fixed wire layouts,
# core_worker.proto:389 PushTaskRequest/Reply). The five hottest frame
# kinds — task push, inline-value result, stored-result stub, error
# result, and object fetch — get hand-packed struct layouts; user
# payloads stay opaque bytes inside them (pickled once, by the layer
# that owns them — the frame itself adds zero pickle tax). Everything
# else falls back to the cloudpickle envelope.
#
# Frame discrimination is by leading magic byte: cloudpickle protocol-2+
# streams always begin 0x80, so 0x01 (typed) and 0x02 (batch) are
# unambiguous. decode_typed returns None for non-typed frames.
# ---------------------------------------------------------------------------

import struct as _struct

MAGIC_TYPED = 0x01
MAGIC_BATCH = 0x02
MAGIC_SEQ = 0x03

# Seq envelope (v7, extended v9): (magic, seq u64, ack u64, epoch u32)
# prefix on every post-handshake session frame. seq is the sender's
# monotonic frame number (0 = pure ack, empty inner payload); ack is
# the highest seq the sender has received from the peer (cumulative,
# prunes the peer's resend ring); epoch is the session incarnation's
# node_epoch (v9 fencing: a frame stamped with a stale incarnation is
# dropped and counted, never applied; 0 = epoch not yet learned,
# pre-registration handshake traffic only).
_SEQ = _struct.Struct(">BQQI")


#: Size of the seq envelope; channel pre-sizes its reusable header
#: buffer with this so the envelope is packed in place, never prepended.
SEQ_SIZE = _SEQ.size


def pack_seq_into(buf, offset: int, seq: int, ack: int,
                  epoch: int = 0) -> None:
    """Pack the seq envelope into a caller-owned header buffer
    (zero-copy framing: the payload is never re-materialized to prepend
    the envelope)."""
    _SEQ.pack_into(buf, offset, MAGIC_SEQ, seq, ack, epoch)


def wrap_seq(seq: int, ack: int, payload: bytes, epoch: int = 0) -> bytes:
    """Prefix a frame payload with the seq envelope."""
    return _SEQ.pack(MAGIC_SEQ, seq, ack, epoch) + payload


def unwrap_seq(payload: bytes):
    """(seq, ack, epoch, inner) for enveloped frames, None for raw
    ones."""
    if len(payload) >= _SEQ.size and payload[0] == MAGIC_SEQ:
        _, seq, ack, epoch = _SEQ.unpack_from(payload)
        return seq, ack, epoch, payload[_SEQ.size:]
    return None

_OP_EXECUTE_TASK = 0x01
_OP_REPLY_VALUE = 0x02
_OP_REPLY_STORED = 0x03
_OP_REPLY_ERROR = 0x04
_OP_REPLY_RAW = 0x05
_OP_FETCH_OBJECT = 0x06

_HDR = _struct.Struct(">BB")
_U32 = _struct.Struct(">I")
_BATCH_HDR = _struct.Struct(">BI")  # MAGIC_BATCH + frame count
_U64 = _struct.Struct(">Q")
_F64 = _struct.Struct(">d")


def _part_len(p) -> int:
    """Byte length of a part — memoryview len() counts elements, not
    bytes, so a non-'B'-format view would corrupt length words."""
    return p.nbytes if isinstance(p, memoryview) else len(p)

_F_PLAIN_ARGS = 1
_F_LEASE = 2
_F_CLASS = 4
_F_FN_BYTES = 8
_F_EXTRA = 16

#: execute_task fields handled natively; anything else rides the
#: pickled `extra` tail (runtime_env, tpu_ids) or forces full fallback.
_EXEC_NATIVE_KEYS = frozenset({
    "type", "req_id", "fn_id", "payload", "name", "task_id", "num_cpus",
    "store_limit", "num_returns", "lease_id", "class_id", "plain_args",
    "fn_bytes", "runtime_env", "tpu_ids"})


def _pb(buf: list, b: bytes, wide: bool = False) -> None:
    buf.append((_U64 if wide else _U32).pack(len(b)))
    buf.append(b)


def _encode_execute_task(msg: Dict[str, Any]):
    if not _EXEC_NATIVE_KEYS.issuperset(msg):
        return None  # unknown field: the pickle envelope carries it
    flags = 0
    extra = {}
    if msg.get("runtime_env"):
        extra["runtime_env"] = msg["runtime_env"]
    if msg.get("tpu_ids"):
        extra["tpu_ids"] = msg["tpu_ids"]
    if msg.get("plain_args"):
        flags |= _F_PLAIN_ARGS
    lease = msg.get("lease_id")
    if lease is not None:
        flags |= _F_LEASE
    class_id = msg.get("class_id")
    if class_id is not None:
        flags |= _F_CLASS
    fn_bytes = msg.get("fn_bytes")
    if fn_bytes is not None:
        flags |= _F_FN_BYTES
    if extra:
        flags |= _F_EXTRA
    out = [_HDR.pack(MAGIC_TYPED, _OP_EXECUTE_TASK),
           _U64.pack(msg["req_id"]),
           _struct.pack(">B", flags),
           _F64.pack(float(msg.get("num_cpus", 1.0) or 0.0)),
           _U64.pack(int(msg.get("store_limit", 0) or 0)),
           _U32.pack(int(msg.get("num_returns", 1) or 1))]
    _pb(out, msg["fn_id"])
    _pb(out, msg["payload"], wide=True)
    _pb(out, (msg.get("name") or "").encode())
    _pb(out, (msg.get("task_id") or "").encode())
    if flags & _F_LEASE:
        _pb(out, lease.encode())
    if flags & _F_CLASS:
        _pb(out, class_id.encode())
    if flags & _F_FN_BYTES:
        _pb(out, fn_bytes, wide=True)
    if flags & _F_EXTRA:
        import pickle as _pickle
        _pb(out, _pickle.dumps(extra), wide=True)
    return out


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def take(self, st: _struct.Struct):
        v = st.unpack_from(self.buf, self.pos)
        self.pos += st.size
        return v[0] if len(v) == 1 else v

    def take_bytes(self, wide: bool = False) -> bytes:
        n = self.take(_U64 if wide else _U32)
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise WireSchemaError("typed frame truncated")
        self.pos += n
        return b


def _decode_execute_task(r: "_Reader") -> Dict[str, Any]:
    msg: Dict[str, Any] = {"type": "execute_task"}
    msg["req_id"] = r.take(_U64)
    flags = r.take(_struct.Struct(">B"))
    msg["num_cpus"] = r.take(_F64)
    msg["store_limit"] = r.take(_U64)
    msg["num_returns"] = r.take(_U32)
    msg["fn_id"] = r.take_bytes()
    msg["payload"] = r.take_bytes(wide=True)
    name = r.take_bytes().decode()
    if name:
        msg["name"] = name
    task_id = r.take_bytes().decode()
    if task_id:
        msg["task_id"] = task_id
    if flags & _F_PLAIN_ARGS:
        msg["plain_args"] = True
    if flags & _F_LEASE:
        msg["lease_id"] = r.take_bytes().decode()
    if flags & _F_CLASS:
        msg["class_id"] = r.take_bytes().decode()
    if flags & _F_FN_BYTES:
        msg["fn_bytes"] = r.take_bytes(wide=True)
    if flags & _F_EXTRA:
        import pickle as _pickle
        msg.update(_pickle.loads(r.take_bytes(wide=True)))
    return msg


def _encode_reply(msg: Dict[str, Any]):
    keys = set(msg)
    req_id = msg.get("req_id")
    if not isinstance(req_id, int) or req_id < 0:
        return None
    if msg.get("ok") is True:
        if keys == {"req_id", "ok", "value"}:
            v = msg["value"]
            if isinstance(v, (list, tuple)):
                # Pickle-5 OOB part list (serialization.serialize_parts):
                # the buffers ride behind the length word by reference,
                # never joined sender-side.
                return [_HDR.pack(MAGIC_TYPED, _OP_REPLY_VALUE),
                        _U64.pack(req_id),
                        _U64.pack(sum(_part_len(p) for p in v)), *v]
            if isinstance(v, bytes):
                return [_HDR.pack(MAGIC_TYPED, _OP_REPLY_VALUE),
                        _U64.pack(req_id), _U64.pack(len(v)), v]
            return None
        if keys == {"req_id", "ok", "stored_key", "size"}:
            kb = msg["stored_key"].encode()
            return [_HDR.pack(MAGIC_TYPED, _OP_REPLY_STORED),
                    _U64.pack(req_id), _U32.pack(len(kb)), kb,
                    _U64.pack(int(msg["size"]))]
        if keys == {"req_id", "ok", "raw"} and \
                isinstance(msg["raw"], bytes):
            return [_HDR.pack(MAGIC_TYPED, _OP_REPLY_RAW),
                    _U64.pack(req_id), _U64.pack(len(msg["raw"])),
                    msg["raw"]]
        return None
    if msg.get("ok") is False and keys == {"req_id", "ok", "error"} and \
            isinstance(msg["error"], bytes):
        return [_HDR.pack(MAGIC_TYPED, _OP_REPLY_ERROR),
                _U64.pack(req_id), _U64.pack(len(msg["error"])),
                msg["error"]]
    return None


def _encode_fetch_object(msg: Dict[str, Any]):
    if set(msg) != {"type", "req_id", "key"}:
        return None
    kb = msg["key"].encode()
    return [_HDR.pack(MAGIC_TYPED, _OP_FETCH_OBJECT),
            _U64.pack(msg["req_id"]), _U32.pack(len(kb)), kb]


def encode_typed_parts(msg: Dict[str, Any]):
    """Part list for a hot-path control message — header/length structs
    as small bytes objects, user payload buffers BY REFERENCE (never
    copied) — or None when the message must ride the cloudpickle
    envelope instead. NEVER raises — a shape the layout cannot carry
    simply falls back."""
    try:
        mtype = msg.get("type")
        if mtype == "execute_task":
            return _encode_execute_task(msg)
        if mtype == "fetch_object":
            return _encode_fetch_object(msg)
        if mtype is None:
            return _encode_reply(msg)
    except Exception:  # noqa: BLE001 - fallback is always correct
        return None
    return None


def encode_typed(msg: Dict[str, Any]):
    """Joined form of :func:`encode_typed_parts` (or None)."""
    parts = encode_typed_parts(msg)
    return b"".join(parts) if parts is not None else None


def decode_typed(buf: bytes):
    """Decode a typed (0x01) frame back to its dict form, or None when
    the frame is not typed (pickle envelope / batch)."""
    if not buf or buf[0] != MAGIC_TYPED:
        return None
    r = _Reader(buf, 1)
    op = r.take(_struct.Struct(">B"))
    if op == _OP_EXECUTE_TASK:
        return _decode_execute_task(r)
    if op == _OP_REPLY_VALUE:
        return {"req_id": r.take(_U64), "ok": True,
                "value": r.take_bytes(wide=True)}
    if op == _OP_REPLY_STORED:
        req_id = r.take(_U64)
        key = r.take_bytes().decode()
        return {"req_id": req_id, "ok": True, "stored_key": key,
                "size": r.take(_U64)}
    if op == _OP_REPLY_RAW:
        return {"req_id": r.take(_U64), "ok": True,
                "raw": r.take_bytes(wide=True)}
    if op == _OP_REPLY_ERROR:
        return {"req_id": r.take(_U64), "ok": False,
                "error": r.take_bytes(wide=True)}
    if op == _OP_FETCH_OBJECT:
        return {"type": "fetch_object", "req_id": r.take(_U64),
                "key": r.take_bytes().decode()}
    raise WireSchemaError(f"unknown typed wire op 0x{op:02x}")


def encode_batch_parts(frames_parts) -> list:
    """Flat part list for a batch frame built from per-message part
    lists — payload buffers stay by reference, only the batch header
    and per-frame length prefixes are materialized."""
    out = [_BATCH_HDR.pack(MAGIC_BATCH, len(frames_parts))]
    for parts in frames_parts:
        out.append(_U64.pack(sum(_part_len(p) for p in parts)))
        out.extend(parts)
    return out


def encode_batch(frames) -> bytes:
    """Pack pre-encoded (joined) frames into one joined batch frame."""
    return b"".join(encode_batch_parts([[f] for f in frames]))


def decode_batch(buf: bytes):
    """Unpack a batch (0x02) frame into its per-message frames, or None
    when the frame is not a batch."""
    if not buf or buf[0] != MAGIC_BATCH:
        return None
    r = _Reader(buf, 1)
    n = r.take(_U32)
    return [r.take_bytes(wide=True) for _ in range(n)]
